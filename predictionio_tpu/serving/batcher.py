"""Micro-batching for the query path (beyond-parity).

The reference serves queries one at a time per request thread
(CreateServer.scala:515 "TODO: Parallelize"). On a TPU the per-call
dispatch + device->host fetch dominates single-query latency, so under
concurrent load the server can coalesce queries that arrive within a short
window into ONE batched device call (Algorithm.batch_predict) and fan the
results back out — the standard accelerator-serving pattern.

Opt-in via ServerConfig.micro_batch > 1. Coalescing is DRAIN-FIRST:
each dispatch takes everything that queued while the previous batch was
on the device — under load the queue grows, so batches grow, which is
the self-regulating part that delivers the throughput. On top of that,
the door is held open (up to `max_wait_ms`) only while MORE queries are
known to be in flight (submitted, unanswered, not in this batch) than
the batch holds: that covers the instants between a submit's counter
increment and its queue put, and nothing else — a query still being
HTTP-parsed is invisible to the server and no window can wait for it
honestly. A lone closed-loop client (serial requests) always sees
`batch == inflight` and dispatches immediately with no window cost; so
does an idle server. Two earlier designs were rejected by measurement:
an unconditional window (rounds 2-3) charged every serial query the
full window, and an EMA-of-arrival-gaps gate charged them the same way
because one closed-loop client's gaps equal the service time — dense by
any rate heuristic. `latency_budget_ms`, when set, caps how long the
OLDEST query may sit in the coalescing stage (the knob for
tail-latency-sensitive deployments; it bounds queueing delay, not
device time).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Optional

from predictionio_tpu.obs.slo import lock_probe, timed_acquire

logger = logging.getLogger(__name__)


class ShedError(RuntimeError):
    """Load shed: the queue's wait bound exceeds the request's deadline,
    so the server answers 503 + Retry-After NOW instead of burning a
    thread on an answer the client will have abandoned (ISSUE 3
    graceful degradation). ``retry_after_s`` is the server's own wait
    bound — the honest earliest time a retry could be served."""

    http_status = 503

    def __init__(self, wait_bound_s: float, deadline_s: float):
        super().__init__(
            f"overloaded: queue wait bound {wait_bound_s * 1000:.0f}ms "
            f"exceeds request deadline {deadline_s * 1000:.0f}ms")
        self.retry_after_s = wait_bound_s


class ShutdownError(RuntimeError):
    """The micro-batcher is stopping; queued requests fail explicitly
    instead of hanging their futures."""

    http_status = 503

    def __init__(self, message: str = "server shutting down"):
        super().__init__(message)


class _Pending:
    __slots__ = ("query", "event", "result", "error", "t_enqueue",
                 "trace_id", "batch_trace_id")

    def __init__(self, query):
        self.query = query
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()
        # ingress trace of the submitting request thread; the dispatch
        # loop links it to the batch_predict trace (and back)
        self.trace_id: Optional[str] = None
        self.batch_trace_id: Optional[str] = None


class MicroBatcher:
    def __init__(self, process_batch, max_batch: int = 32,
                 max_wait_ms: float = 5.0,
                 latency_budget_ms: Optional[float] = None,
                 metrics=None):
        """process_batch: fn(List[query]) -> List[result]. `metrics`:
        an obs.MetricsRegistry to mount the coalescing telemetry on —
        the counters below stay the single source of truth (stats()
        reads them directly) and the registry samples them at scrape
        time; the batch-wait distribution is a native histogram."""
        self.process_batch = process_batch
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.latency_budget_s = (latency_budget_ms / 1000.0
                                 if latency_budget_ms is not None else None)
        # realized coalescing telemetry (read via /stats.json): whether
        # concurrent load actually forms full batches is THE datum for
        # tuning micro_batch_wait_ms on a given link
        self.n_batches = 0
        self.n_queries = 0
        self.max_batch_seen = 0
        # batches dispatched without ever blocking on the window —
        # includes idle/serial traffic AND fully-drained batches under
        # saturated load; (batches - immediateBatches) is the number of
        # dispatches that actually waited for a straggler
        self.n_immediate = 0
        # WHY each dispatch closed its batch — the attribution data for a
        # realized avg batch below micro_batch under concurrent load
        # (e.g. the pinned serve_avg_batch_size=8.0 at micro_batch=16):
        #   exitFullBatch   — hit max_batch (device-bound; raising
        #                     micro_batch could coalesce more)
        #   exitDrainGate   — queue empty and inflight <= batch: the
        #                     CLIENT POOL was the limit (every submitted-
        #                     unanswered query is already in this batch —
        #                     with N closed-loop clients the steady-state
        #                     batch is at most N no matter the window)
        #   exitWindow      — the hold expired waiting on a counted
        #                     straggler (max_wait_ms / latency budget
        #                     bound; raising the window could help)
        self.n_exit_full = 0
        self.n_exit_drain_gate = 0
        self.n_exit_window = 0
        # sum of inflight observed at dispatch: avg inflight is the
        # effective concurrent-client count the batcher actually saw
        self.inflight_at_dispatch_sum = 0
        # queries submitted and not yet answered — the adaptive window's
        # signal: hold only while the batch is smaller than this
        self._inflight = 0
        self._flight_lock = threading.Lock()
        # deadline shedding (ISSUE 3): EWMA of per-batch service time
        # feeds the queue wait bound; requests whose deadline the bound
        # already exceeds are refused at admission with 503+Retry-After
        self._service_ewma_s = 0.0
        self.n_shed = 0
        self.n_shutdown_failed = 0
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        # contention probe (ISSUE 6): request threads' wait on the
        # admission lock, as pio_lock_wait_seconds{lock=batcher_inflight}
        self._lock_wait = lock_probe("batcher_inflight")
        self.wait_hist = None
        if metrics is not None:
            self.wait_hist = metrics.histogram(
                "pio_engine_batch_wait_seconds",
                "Per-query time in the coalescing stage "
                "(enqueue -> dispatch)")
            metrics.counter_func(
                "pio_engine_batches_total", "Micro-batch dispatches",
                lambda: self.n_batches)
            metrics.counter_func(
                "pio_engine_batched_queries_total",
                "Queries through the micro-batcher",
                lambda: self.n_queries)
            metrics.counter_func(
                "pio_engine_immediate_batches_total",
                "Dispatches that never blocked on the window",
                lambda: self.n_immediate)
            metrics.gauge_func(
                "pio_engine_max_batch_size", "Largest coalesced batch",
                lambda: self.max_batch_seen)
            metrics.counter_func(
                "pio_engine_batch_exits_total",
                "Why each dispatch closed its batch (attributes a "
                "sub-micro_batch realized batch size: drain_gate = "
                "client pool was the limit, window = straggler hold "
                "expired, full = max_batch hit)",
                lambda: [({"reason": "full"}, self.n_exit_full),
                         ({"reason": "drain_gate"},
                          self.n_exit_drain_gate),
                         ({"reason": "window"}, self.n_exit_window)])
            metrics.gauge_func(
                "pio_engine_avg_inflight_at_dispatch",
                "Mean submitted-unanswered queries at dispatch (the "
                "effective concurrent-client count)",
                lambda: round(self.inflight_at_dispatch_sum
                              / self.n_batches, 3)
                if self.n_batches else 0.0)
            metrics.counter_func(
                "pio_engine_shed_total",
                "Queries refused at admission because the queue wait "
                "bound exceeded their deadline (503 + Retry-After)",
                lambda: self.n_shed)
            metrics.gauge_func(
                "pio_engine_queue_wait_bound_seconds",
                "Current admission-time wait bound (queue depth x EWMA "
                "batch service time + window)",
                lambda: self.queue_wait_bound_s())
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stats(self) -> dict:
        # the counters are updated together by the dispatch thread just
        # before each process_batch call; snapshotting queries BEFORE
        # batches keeps the derived average internally consistent
        # (avg <= max_batch) even when a batch lands mid-read
        nq = self.n_queries
        nb = self.n_batches
        mx = self.max_batch_seen
        return {"batches": nb, "batchedQueries": nq,
                "avgBatchSize": (nq / nb if nb else 0.0),
                "maxBatchSize": mx,
                "immediateBatches": self.n_immediate,
                "exitFullBatch": self.n_exit_full,
                "exitDrainGate": self.n_exit_drain_gate,
                "exitWindow": self.n_exit_window,
                "shedQueries": self.n_shed,
                "queueWaitBoundSec": self.queue_wait_bound_s(),
                "avgInflightAtDispatch": (
                    self.inflight_at_dispatch_sum / nb if nb else 0.0)}

    def queue_wait_bound_s(self) -> float:
        """Upper bound on how long a query enqueued NOW waits before its
        batch dispatches: the batch currently on the device (if any)
        plus every queued batch ahead of it costs one EWMA service time
        each, plus one coalescing window. An idle batcher returns 0 —
        the drain gate dispatches a lone query immediately, so nothing
        with a deadline is ever shed at zero load. This is the
        admission-control signal AND the Retry-After value on sheds —
        the server's honest estimate, not a constant."""
        depth = self._q.qsize()
        # inflight > queued means a dispatched batch occupies the device
        busy = 1 if self._inflight > depth else 0
        batches_ahead = (depth + self.max_batch - 1) // self.max_batch \
            + busy
        if batches_ahead == 0:
            return 0.0
        return batches_ahead * self._service_ewma_s + self.max_wait_s

    def submit(self, query, deadline_s: Optional[float] = None) -> Any:
        """Blocking: enqueue and wait for the batched result.

        ``deadline_s``: the request's remaining deadline budget
        (propagated from HTTP ingress). When the queue's wait bound
        already exceeds it the query is shed at admission with
        ``ShedError`` (503 + Retry-After) — wasted-work protection
        under saturation while in-deadline queries still answer."""
        from predictionio_tpu.obs import TRACER
        if deadline_s is not None:
            bound = self.queue_wait_bound_s()
            if bound > deadline_s:
                self.n_shed += 1
                from predictionio_tpu.obs.flight import FLIGHT
                FLIGHT.record("shed", coalesce_s=1.0,
                              waitBoundS=round(bound, 4),
                              deadlineS=round(deadline_s, 4))
                raise ShedError(bound, deadline_s)
        p = _Pending(query)
        p.trace_id = TRACER.current_trace_id()
        with timed_acquire(self._flight_lock, self._lock_wait):
            # check-and-enqueue is atomic with stop()'s set-and-sweep
            # (both under _flight_lock), so no submitter can slip a
            # pending item in after the shutdown sweep ran
            if self._stop.is_set():
                raise ShutdownError("micro-batcher is shut down")
            self._inflight += 1
            self._q.put(p)
        with TRACER.span("batch_wait"):
            p.event.wait()
        if p.batch_trace_id is not None:
            # tie this query's ingress trace to the coalesced window
            # that answered it (the dispatch loop recorded the reverse
            # link before waking us)
            cur = TRACER.current_trace()
            if cur is not None:
                cur.link(p.batch_trace_id)
        if p.error is not None:
            raise p.error
        return p.result

    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            t_first = time.perf_counter()   # batch-formation stage t0
            batch = [first]
            # Drain-first batching: take the backlog that accumulated
            # while the previous batch was on the device (the
            # self-regulating coalescing), then hold the door open ONLY
            # while more queries are known in flight (submitted,
            # unanswered, not yet in this batch) — i.e. between their
            # counter increment and queue put, microseconds away. When
            # batch == inflight nobody else is known to be coming: a
            # closed-loop serial client, or an idle server, dispatches
            # with zero window cost. max_wait bounds the hold in case a
            # counted straggler stalls before reaching the queue.
            held = False
            exit_reason = "full"   # loop falls through => max_batch hit
            deadline = time.perf_counter() + self.max_wait_s
            if self.latency_budget_s is not None:
                # cap the oldest query's time in the coalescing stage
                deadline = min(deadline,
                               first.t_enqueue + self.latency_budget_s)
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get_nowait())
                    continue
                except queue.Empty:
                    pass
                if self._inflight <= len(batch):
                    exit_reason = "drain_gate"
                    break          # nobody else known in flight
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    exit_reason = "window"
                    break
                held = True
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    exit_reason = "window"
                    break
            self.n_batches += 1
            self.n_queries += len(batch)
            self.max_batch_seen = max(self.max_batch_seen, len(batch))
            self.inflight_at_dispatch_sum += self._inflight
            if exit_reason == "full":
                self.n_exit_full += 1
            elif exit_reason == "drain_gate":
                self.n_exit_drain_gate += 1
            else:
                self.n_exit_window += 1
            if not held:
                self.n_immediate += 1
            if self._stop.is_set():
                # stop landed while this batch was collecting: fail its
                # members explicitly rather than racing a device call
                # against interpreter teardown
                with self._flight_lock:
                    self._inflight -= len(batch)
                for p in batch:
                    self.n_shutdown_failed += 1
                    p.error = ShutdownError()
                    p.event.set()
                continue
            t_dispatch = time.perf_counter()
            if self.wait_hist is not None:
                for p in batch:
                    self.wait_hist.observe(t_dispatch - p.t_enqueue)
            try:
                results = self._run_batch(
                    batch, formation_s=t_dispatch - t_first)
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"batch handler returned {len(results)} results "
                        f"for {len(batch)} queries")
                with self._flight_lock:
                    self._inflight -= len(batch)
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # propagate to every waiter
                with self._flight_lock:
                    self._inflight -= len(batch)
                for p in batch:
                    p.error = e
                    p.event.set()
            # EWMA of batch service time: the queue wait bound's basis.
            # Updated on the dispatch thread only; alpha 0.2 smooths
            # device-warmup spikes without lagging a real slowdown.
            dt = time.perf_counter() - t_dispatch
            self._service_ewma_s = (dt if self._service_ewma_s == 0.0
                                    else 0.8 * self._service_ewma_s
                                    + 0.2 * dt)

    def _run_batch(self, batch, formation_s: float = 0.0):
        """One dispatch. When any member carries an ingress trace, the
        device call runs under its own batch_predict trace linked both
        ways — the dispatch thread has no request context, so the link
        set is how /traces.json ties a query to its window.
        ``formation_s`` (first dequeue -> dispatch) rides the trace as
        the slow-query waterfall's batch_formation stage."""
        member_traces = [p.trace_id for p in batch if p.trace_id]
        if not member_traces:
            return self.process_batch([p.query for p in batch])
        from predictionio_tpu.obs import TRACER
        with TRACER.trace("batch_predict", batch=len(batch),
                          formationMs=round(formation_s * 1000.0, 3)
                          ) as bt:
            for tid in member_traces:
                bt.link(tid)
            for p in batch:
                p.batch_trace_id = bt.trace_id
            return self.process_batch([p.query for p in batch])

    def stop(self, join_timeout_s: float = 10.0):
        """Drain-on-stop: the dispatch thread is given time to finish
        the batch on the device, then every request still queued (or
        collected but not yet dispatched) fails with an explicit
        "server shutting down" 503 — no future ever hangs. Atomic with
        submit()'s check-and-enqueue via _flight_lock, so nothing can
        enqueue after the sweep."""
        self._stop.set()
        self._thread.join(timeout=join_timeout_s)
        if self._thread.is_alive():
            logger.warning(
                "micro-batcher dispatch thread still busy after %.1fs; "
                "sweeping the queue anyway", join_timeout_s)
        with self._flight_lock:
            while True:
                try:
                    p = self._q.get_nowait()
                except queue.Empty:
                    break
                self._inflight -= 1
                self.n_shutdown_failed += 1
                p.error = ShutdownError()
                p.event.set()
