"""Micro-batching for the query path (beyond-parity).

The reference serves queries one at a time per request thread
(CreateServer.scala:515 "TODO: Parallelize"). On a TPU the per-call
dispatch + device->host fetch dominates single-query latency, so under
concurrent load the server can coalesce queries that arrive within a short
window into ONE batched device call (Algorithm.batch_predict) and fan the
results back out — the standard accelerator-serving pattern.

Opt-in via ServerConfig.micro_batch > 1. The coalescing window is
ADAPTIVE: each dispatch holds the door open for up to `max_wait_ms` only
while the recent inter-arrival rate says more queries are actually
coming (EMA of arrival gaps <= window); an isolated query on an idle
server dispatches immediately and pays no window at all. The window also
closes early the moment the batch fills, and `latency_budget_ms`, when
set, caps how long the OLDEST query in a batch may sit in the coalescing
stage regardless of arrival rate (the knob for tail-latency-sensitive
deployments; it bounds queueing delay, not device time).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("query", "event", "result", "error", "t_enqueue")

    def __init__(self, query):
        self.query = query
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()


class MicroBatcher:
    def __init__(self, process_batch, max_batch: int = 32,
                 max_wait_ms: float = 5.0,
                 latency_budget_ms: Optional[float] = None):
        """process_batch: fn(List[query]) -> List[result]."""
        self.process_batch = process_batch
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.latency_budget_s = (latency_budget_ms / 1000.0
                                 if latency_budget_ms is not None else None)
        # realized coalescing telemetry (read via /stats.json): whether
        # concurrent load actually forms full batches is THE datum for
        # tuning micro_batch_wait_ms on a given link
        self.n_batches = 0
        self.n_queries = 0
        self.max_batch_seen = 0
        # batches dispatched without holding the window (idle fast path)
        self.n_immediate = 0
        # adaptive-window state, touched only by the dispatch thread:
        # EMA of query inter-arrival gaps; None until two arrivals seen
        self._ema_gap: Optional[float] = None
        self._prev_arrival: Optional[float] = None
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stats(self) -> dict:
        # the counters are updated together by the dispatch thread just
        # before each process_batch call; snapshotting queries BEFORE
        # batches keeps the derived average internally consistent
        # (avg <= max_batch) even when a batch lands mid-read
        nq = self.n_queries
        nb = self.n_batches
        mx = self.max_batch_seen
        return {"batches": nb, "batchedQueries": nq,
                "avgBatchSize": (nq / nb if nb else 0.0),
                "maxBatchSize": mx,
                "immediateBatches": self.n_immediate}

    def submit(self, query) -> Any:
        """Blocking: enqueue and wait for the batched result."""
        p = _Pending(query)
        self._q.put(p)
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def _observe_arrival(self, t_enqueue: float):
        """EMA of inter-arrival gaps (clipped at 1 s so one idle night
        doesn't take minutes of traffic to forget)."""
        if self._prev_arrival is not None:
            gap = min(max(t_enqueue - self._prev_arrival, 0.0), 1.0)
            self._ema_gap = (gap if self._ema_gap is None
                             else 0.7 * self._ema_gap + 0.3 * gap)
        self._prev_arrival = t_enqueue

    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            self._observe_arrival(first.t_enqueue)
            batch = [first]
            # adaptive batching: drain the backlog that accumulated while
            # the previous batch was on the device, then hold the door
            # open for at most max_wait so requests mid-flight through
            # HTTP parsing (threads arrive staggered under the GIL) join
            # this batch instead of forming a tiny next one — but ONLY
            # when the recent arrival rate says anyone else is coming
            # (EMA gap <= window). An idle server dispatches immediately,
            # so the window costs isolated queries nothing; under 16-way
            # concurrent load it is what turns the stream into batches of
            # ~16 rather than ~4.
            hold = (self._ema_gap is not None
                    and self._ema_gap <= self.max_wait_s)
            deadline = time.perf_counter() + (self.max_wait_s if hold
                                              else 0.0)
            if self.latency_budget_s is not None:
                # cap the oldest query's time in the coalescing stage
                deadline = min(deadline,
                               first.t_enqueue + self.latency_budget_s)
            while len(batch) < self.max_batch:
                try:
                    p = self._q.get_nowait()
                except queue.Empty:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        p = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                self._observe_arrival(p.t_enqueue)
                batch.append(p)
            self.n_batches += 1
            self.n_queries += len(batch)
            self.max_batch_seen = max(self.max_batch_seen, len(batch))
            if not hold:
                self.n_immediate += 1
            try:
                results = self.process_batch([p.query for p in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"batch handler returned {len(results)} results "
                        f"for {len(batch)} queries")
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # propagate to every waiter
                for p in batch:
                    p.error = e
                    p.event.set()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)
