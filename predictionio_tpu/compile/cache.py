"""Managed persistent XLA compilation cache.

JAX can serialize every backend-compiled executable to disk and
deserialize it in any later process whose computation hashes the same
(``jax_compilation_cache_dir``). This module owns that cache for the
whole product:

- **Location** — ``<root>/<salt>/`` where root is
  ``$PIO_XLA_CACHE_DIR`` or ``base_dir()/xla_cache``; an explicit
  ``$JAX_COMPILATION_CACHE_DIR`` wins outright (operator override,
  unsalted — they own its lifecycle). ``PIO_XLA_CACHE=off`` disables.
- **Salt** — a fingerprint of the kernel sources (``ops/*.py``,
  ``online/fold_in.py``, ``compile/aot.py``) plus the jax version.
  JAX's own cache key already hashes the exact computation, so a stale
  entry can never be *wrong* — the salt keeps the lifecycle clean: a
  kernel change rolls the directory, ``pio cache clear`` removes dead
  salts, and disk growth is bounded by live-kernel programs.
- **Thresholds** — min-compile-time and min-entry-size are zeroed:
  the serve/fold programs this repo cares about are small and fast to
  compile on CPU but minutes on TPU; caching everything costs little
  and makes the CPU test container exercise the same code path.
- **Counters** — ``pio_compile_pcache_hits_total{executable}`` /
  ``..._misses_total{executable}``: jax fires cache hit/miss events on
  the compiling thread, so obs/costmon's executable label attributes
  them to the dispatch scope that paid (or skipped) the compile.

``enable_persistent_cache()`` is idempotent and safe before or after
jax's first use — config updates apply to every later compile.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_enabled_dir: Optional[str] = None
_salt_memo: Optional[str] = None

#: modules whose source changes must roll the cache directory — the
#: files that define traced programs (keep in sync with the docstring)
_KERNEL_GLOBS = ("ops", "online/fold_in.py", "compile/aot.py")


def cache_disabled() -> bool:
    return os.environ.get("PIO_XLA_CACHE", "").lower() in (
        "off", "0", "false", "no")


def cache_root() -> str:
    env = os.environ.get("PIO_XLA_CACHE_DIR")
    if env:
        return env
    from predictionio_tpu.data.storage.registry import base_dir
    return os.path.join(base_dir(), "xla_cache")


def _kernel_files():
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in _KERNEL_GLOBS:
        p = os.path.join(pkg, rel)
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.endswith(".py"):
                    yield os.path.join(p, name)
        elif os.path.isfile(p):
            yield p


def cache_salt() -> str:
    """12-hex fingerprint of the kernel sources + jax version. Memoized
    — the sources cannot change under a running process."""
    global _salt_memo
    if _salt_memo is not None:
        return _salt_memo
    h = hashlib.sha256()
    try:
        import jax
        h.update(jax.__version__.encode())
    except Exception:
        pass
    for path in _kernel_files():
        h.update(os.path.basename(path).encode())
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:
            continue
    _salt_memo = h.hexdigest()[:12]
    return _salt_memo


def enable_persistent_cache(root: Optional[str] = None) -> Optional[str]:
    """Point jax at the salted persistent cache directory. Idempotent;
    returns the active directory, or None when disabled/unavailable.
    An explicit ``JAX_COMPILATION_CACHE_DIR`` is honored as-is."""
    global _enabled_dir
    if cache_disabled():
        return None
    if _enabled_dir is not None and root is None:
        return _enabled_dir
    # salt hashing and mkdir are file I/O — do them before taking the
    # lock (first callers race harmlessly: same dir, idempotent config)
    env_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env_dir and root is None:
        cache_dir = env_dir
    else:
        cache_dir = os.path.join(root or cache_root(), cache_salt())
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax
        with _lock:
            if _enabled_dir is not None and root is None:
                return _enabled_dir
            # jax latches cache usability at the FIRST compile of the
            # process (and the directory at first initialization): a
            # process that already compiled before this call — or a dir
            # change from tests/operator re-point — must reset, or the
            # new configuration is silently ignored
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:
                logger.debug("cache reset unavailable", exc_info=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # cache EVERYTHING: the serve/fold programs are small on
            # CPU (the test container) but minutes of XLA on TPU, and
            # the acceptance tests measure the same code path on both
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            _enabled_dir = cache_dir
    except Exception:
        logger.debug("persistent compile cache unavailable",
                     exc_info=True)
        return None
    # per-executable hit/miss attribution rides costmon's label
    from predictionio_tpu.obs import costmon
    costmon.install()
    return _enabled_dir


def disable_persistent_cache() -> None:
    """Detach jax from the persistent cache (tests; an operator uses
    PIO_XLA_CACHE=off before process start instead). Safe to call when
    never enabled."""
    global _enabled_dir
    with _lock:
        if _enabled_dir is None:
            return
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", None)
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            logger.debug("persistent cache disable failed",
                         exc_info=True)
        _enabled_dir = None


def persistent_cache_enabled() -> bool:
    return _enabled_dir is not None


def active_cache_dir() -> Optional[str]:
    return _enabled_dir


def _dir_stats(path: str):
    entries = 0
    nbytes = 0
    try:
        for name in os.listdir(path):
            p = os.path.join(path, name)
            if os.path.isfile(p):
                entries += 1
                nbytes += os.path.getsize(p)
    except OSError:
        pass
    return entries, nbytes


def cache_status() -> Dict:
    """Operator view for ``pio cache status`` / ``/stats.json``."""
    from predictionio_tpu.obs import costmon
    out = {
        "enabled": persistent_cache_enabled(),
        "disabledByEnv": cache_disabled(),
        "dir": _enabled_dir,
        "root": None if cache_disabled() else cache_root(),
        "salt": cache_salt(),
        "entries": 0,
        "bytes": 0,
        "hits": costmon.pcache_totals()["hits"],
        "misses": costmon.pcache_totals()["misses"],
    }
    if _enabled_dir:
        out["entries"], out["bytes"] = _dir_stats(_enabled_dir)
    # dead salts left behind by kernel changes (pio cache clear --all
    # removes them)
    root = out["root"]
    if root and os.path.isdir(root):
        out["staleSalts"] = sorted(
            d for d in os.listdir(root)
            if d != cache_salt()
            and os.path.isdir(os.path.join(root, d)))
    return out


def clear_cache(all_salts: bool = False) -> Dict:
    """Remove cached executables. Default scope is the ACTIVE salt
    directory (safe while processes run — jax re-creates entries on
    the next compile); ``all_salts`` also removes dead-salt dirs."""
    import shutil
    removed = 0
    nbytes = 0
    targets = []
    active = _enabled_dir or (
        None if cache_disabled()
        else os.path.join(cache_root(), cache_salt()))
    if active and os.path.isdir(active):
        targets.append(active)
    if all_salts:
        root = cache_root()
        if os.path.isdir(root):
            for d in sorted(os.listdir(root)):
                p = os.path.join(root, d)
                if os.path.isdir(p) and p not in targets:
                    targets.append(p)
    for t in targets:
        e, b = _dir_stats(t)
        removed += e
        nbytes += b
        try:
            shutil.rmtree(t)
            if t == active:
                # the live process keeps writing here: re-create it
                os.makedirs(t, exist_ok=True)
        except OSError:
            logger.warning("pio cache clear: could not remove %s", t,
                           exc_info=True)
    return {"removed": removed, "bytes": nbytes,
            "dirs": [t for t in targets]}
