"""The compile plane (ISSUE 9): kill cold-start.

BENCH_r01 — the only real-TPU capture — put ``warmup_s`` at 231.6
against ``train_s_per_iteration`` of 0.0039: XLA compilation is ~5
orders of magnitude above steady-state, and every ``pio deploy``,
hot-swap, canary stage and rollback used to pay it. This package is
the subsystem that amortizes it away:

- :mod:`predictionio_tpu.compile.cache` — JAX's persistent compilation
  cache, managed: a versioned directory under ``base_dir()/xla_cache``
  whose salt fingerprints the kernel sources (a kernel change rolls
  the directory, so stale entries never shadow fresh code), plus the
  ``pio cache {status,clear}`` surface.
- :mod:`predictionio_tpu.compile.buckets` — the shape-bucket ladder:
  next-pow2-style buckets for vocabulary rows, touched-row counts and
  query batch sizes, so growth INSIDE a bucket never changes a traced
  shape (zero recompiles) and bucket promotion is a single, predictable
  compile that can run before the shape is needed.
- :mod:`predictionio_tpu.compile.aot` — the AOT executable registry:
  hot executables (``batch_predict``, the fold-in solves, the ALS
  sweep, the gate probe) are ``jit(...).lower(...).compile()``-ed at
  deploy/swap time against the bucket ladder and dispatched as held
  ``Compiled`` objects — a warmed serve path runs zero trace and zero
  compile per request.

``PIO_AOT=off`` disables AOT dispatch/warming; ``PIO_XLA_CACHE=off``
disables the persistent cache. Both fall back to plain jit dispatch.
"""

from predictionio_tpu.compile.buckets import (bucket_batch, bucket_rows,
                                              bucket_key, occupancy,
                                              PROMOTE_AT)
from predictionio_tpu.compile.cache import (cache_status, clear_cache,
                                            enable_persistent_cache,
                                            persistent_cache_enabled)
from predictionio_tpu.compile.aot import (AOTRegistry, aot_enabled,
                                          get_aot, shared_jit,
                                          warm_models)

__all__ = [
    "AOTRegistry", "aot_enabled", "bucket_batch", "bucket_key",
    "bucket_rows", "cache_status", "clear_cache",
    "enable_persistent_cache", "get_aot", "occupancy",
    "persistent_cache_enabled", "PROMOTE_AT", "shared_jit",
    "warm_models",
]
