"""AOT executable registry: compile at deploy time, dispatch without
tracing at serve time.

The SNIPPETS.md [1] ``Lowered`` -> ``.lower().compile()`` path, made a
subsystem. An **executable spec** is a builder that, given a bucket-dim
dict (``{"u": 1024, "i": 2048, "b": 16, "k": 16, "r": 10, "p": 1}``),
returns ``(jit_fn, example_args, static_kwargs)``; the registry lowers
and compiles it once per bucket and holds the resulting
``jax.Compiled``. Output avals are whatever the builder's program
emits — the readback plane (ISSUE 19) leans on this: packed buckets
(``p`` > 0) compile programs whose ONE output is the contiguous
ids+quantized-scores payload, so steady-state packing costs zero
serve-time compiles exactly like every other warmed bucket.
A warmed dispatch site then calls the held executable DIRECTLY — zero
Python re-trace, zero XLA compile, zero jit-cache probe on the request
path. Unwarmed buckets fall back to the plain jitted function (whose
compile the persistent cache answers across processes) and schedule a
background adoption so the next request hits.

The registry is also the process's **cached-jit surface**
(``shared_jit``): hot-path modules resolve their jitted helpers here
instead of module-local ``_jits`` dicts, which is the idiom the JAX003/
JAX005 lint rules recognize as compile-plane-routed.

Instrumentation (obs registry):

- ``pio_aot_compile_seconds_total{executable,bucket}`` — AOT compile
  wall per bucket (the deploy-time cost the cache amortizes);
- ``pio_aot_dispatch_hits_total{executable}`` /
  ``..._misses_total`` / ``..._fallbacks_total`` — warmed vs unwarmed
  vs aval-mismatch dispatches;
- ``pio_aot_executables_resident`` — held Compiled count.

``PIO_AOT=off`` turns every dispatch into the fallback call.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from predictionio_tpu.compile.buckets import bucket_key, bucket_label
from predictionio_tpu.obs.costmon import device_timed

logger = logging.getLogger(__name__)


def aot_enabled() -> bool:
    return os.environ.get("PIO_AOT", "").lower() not in (
        "off", "0", "false", "no")


class AOTRegistry:
    """Process-wide registry of AOT-compiled executables, keyed by
    (label, bucket). Thread-safe; compiles happen OUTSIDE the lock (an
    XLA compile may take minutes on TPU — holding the lock would stall
    every dispatch)."""

    def __init__(self, registry=None):
        self._lock = threading.RLock()
        self._builders: Dict[str, Callable] = {}
        self._compiled: Dict[Tuple[str, tuple], Any] = {}
        #: key -> Event set when that key's compile finishes (blocking
        #: callers racing a background compile wait on it)
        self._inflight: Dict[Tuple[str, tuple], threading.Event] = {}
        #: buckets whose compile failed — never retried this process
        #: (a reliably-failing spec would otherwise respawn a minutes-
        #: long XLA compile on every dispatch miss); the jit fallback
        #: keeps serving them
        self._failed: set = set()
        self._threads: set = set()
        self._jits: Dict[str, Any] = {}
        self.compile_seconds = 0.0
        self.compile_count = 0
        if registry is None:
            from predictionio_tpu.obs import get_registry
            registry = get_registry()
        self._c_compile_s = registry.counter(
            "pio_aot_compile_seconds_total",
            "AOT lower+compile wall time by executable and shape "
            "bucket", labelnames=("executable", "bucket"))
        self._c_hits = registry.counter(
            "pio_aot_dispatch_hits_total",
            "dispatches answered by a held AOT executable (no trace, "
            "no compile)", labelnames=("executable",))
        self._c_misses = registry.counter(
            "pio_aot_dispatch_misses_total",
            "dispatches for a bucket with no held executable (served "
            "by the jit fallback; background adoption scheduled)",
            labelnames=("executable",))
        self._c_fallbacks = registry.counter(
            "pio_aot_dispatch_fallbacks_total",
            "held-executable calls rejected on argument avals and "
            "re-served by the jit fallback", labelnames=("executable",))
        # NOTE: the resident-count gauge is registered by get_aot() for
        # the process singleton only — gauge_func is first-registration-
        # wins and a strong closure here would pin whichever instance
        # (a test's throwaway registry) registered first, plus every
        # device executable it holds (the flight-source/incident-
        # provider weakref lesson from ISSUE 6)

    # -- specs --------------------------------------------------------------
    def register(self, label: str, builder: Callable) -> None:
        """``builder(**dims) -> (jit_fn, example_args, static_kwargs)``.
        Re-registration replaces (module reload); held executables for
        the label are kept — they were built from the same source."""
        with self._lock:
            self._builders[label] = builder

    def has_spec(self, label: str) -> bool:
        with self._lock:
            return label in self._builders

    # -- compile ------------------------------------------------------------
    def ensure(self, label: str, dims: Dict[str, int],
               background: bool = False) -> Optional[Any]:
        """Compile (label, bucket) if absent. Blocking by default —
        deploy/swap warming wants the executable held before traffic.
        ``background=True`` returns immediately and adopts the
        executable when the daemon thread finishes."""
        if not aot_enabled():
            return None
        key = (label, bucket_key(dims))
        with self._lock:
            if key in self._compiled:
                return self._compiled[key]
            if key in self._failed:
                return None
            builder = self._builders.get(label)
            if builder is None:
                return None
            pending = self._inflight.get(key)
            if pending is None:
                self._inflight[key] = threading.Event()
        if pending is not None:
            # another thread (e.g. a background promotion) is already
            # compiling this bucket: a blocking caller — a deploy/swap
            # warm whose contract is executable-before-traffic — must
            # WAIT for it, not silently skip the bucket
            if not background:
                pending.wait(timeout=600.0)
                return self._compiled.get(key)
            return None
        if background:
            t = threading.Thread(
                target=self._compile_one, args=(label, dims, key),
                name=f"pio-aot-{label}", daemon=True)
            with self._lock:
                self._threads.add(t)
            t.start()
            return None
        return self._compile_one(label, dims, key)

    def _compile_one(self, label, dims, key):
        from predictionio_tpu.obs import costmon
        try:
            builder = self._builders[label]
            fn, args, statics = builder(**dims)
            t0 = time.perf_counter()
            # compile attribution: the AOT warm IS this executable's
            # compile — charge its label, and let the persistent cache
            # answer it when a previous process already paid
            with costmon.executable(label):
                compiled = fn.lower(*args, **(statics or {})).compile()
            dt = time.perf_counter() - t0
            self._c_compile_s.labels(
                executable=label, bucket=bucket_label(dims)).inc(dt)
            with self._lock:
                self._compiled[key] = compiled
                self.compile_seconds += dt
                self.compile_count += 1
            return compiled
        except Exception:
            with self._lock:
                self._failed.add(key)
            logger.warning("AOT compile of %s %s failed; bucket "
                           "memoized as failed — dispatches fall back "
                           "to jit for this process", label, dims,
                           exc_info=True)
            return None
        finally:
            with self._lock:
                ev = self._inflight.pop(key, None)
                self._threads.discard(threading.current_thread())
            if ev is not None:
                ev.set()

    # -- dispatch -----------------------------------------------------------
    def lookup(self, label: str, dims: Dict[str, int]) -> Optional[Any]:
        return self._compiled.get((label, bucket_key(dims)))

    def dispatch(self, label: str, dims: Dict[str, int],
                 fallback: Callable, *args):
        """Serve-path dispatch: the held executable when the bucket is
        warm (zero trace/compile), else the jit ``fallback`` — whose
        compile the persistent cache covers — plus a background
        adoption so the NEXT request in this bucket hits.

        Every dispatch — held executable and fallback alike — runs
        under ``costmon.device_timed`` (ISSUE 11): dispatch wall is
        counted per request and a 1-in-N sampled sync books true
        device seconds to ``pio_device_time_seconds_total{label}``."""
        if not aot_enabled():
            return device_timed(label, fallback, *args)
        compiled = self._compiled.get((label, bucket_key(dims)))
        if compiled is not None:
            try:
                out = device_timed(label, compiled, *args)
                self._c_hits.labels(executable=label).inc()
                return out
            except TypeError:
                # argument avals drifted off the bucket contract (a
                # caller bug or a dtype surprise): serve correctly via
                # the fallback and make the drift countable
                self._c_fallbacks.labels(executable=label).inc()
                logger.debug("AOT %s %s aval mismatch; fallback",
                             label, dims, exc_info=True)
        else:
            self._c_misses.labels(executable=label).inc()
            self.ensure(label, dims, background=True)
        return device_timed(label, fallback, *args)

    # -- shared cached-jit surface ------------------------------------------
    def adopt(self, key: str, fn) -> Any:
        """Adopt an externally-built jitted callable into the shared-
        jit table (first adoption wins; later adopters get the resident
        instance) — the cached-jit idiom JAX003 recognizes."""
        with self._lock:
            return self._jits.setdefault(key, fn)

    def shared_jit(self, key: str, impl: Callable, **jit_kwargs):
        """Process-wide memoized ``jax.jit`` construction: hot-path
        modules resolve their jitted helpers from the compile plane
        instead of private ``_jits`` dicts, so the registry can report
        them and the lint rules can recognize the idiom. One jit per
        key for the process lifetime."""
        fn = self._jits.get(key)
        if fn is None:
            with self._lock:
                fn = self._jits.get(key)
                if fn is None:
                    import jax
                    fn = jax.jit(impl, **jit_kwargs)
                    self._jits[key] = fn
        return fn

    # -- warming ------------------------------------------------------------
    def warm(self, specs: Iterable[Tuple[str, Dict[str, int]]],
             background: bool = False) -> Dict[str, Any]:
        """Compile every (label, dims) in ``specs``; returns a summary
        the caller can log/record. Blocking unless ``background``."""
        t0 = time.perf_counter()
        compiled = skipped = 0
        for label, dims in specs:
            if not self.has_spec(label):
                skipped += 1
                continue
            before = self.lookup(label, dims) is not None
            self.ensure(label, dims, background=background)
            if not before and self.lookup(label, dims) is not None:
                compiled += 1
        return {"compiled": compiled, "skipped": skipped,
                "wallS": round(time.perf_counter() - t0, 4)}

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Registry state for /stats.json and `pio status --telemetry`:
        executables resident, buckets compiled per label, jit handles,
        compile seconds, dispatch hit/miss counts since start."""
        from predictionio_tpu.obs import costmon

        def _vals(counter):
            return {labels["executable"]: v
                    for labels, v in counter.samples() if labels}

        with self._lock:
            by_label: Dict[str, List[str]] = {}
            for (label, key) in self._compiled:
                by_label.setdefault(label, []).append(
                    "-".join(f"{k}{v}" for k, v in key))
            out = {
                "enabled": aot_enabled(),
                "executablesResident": len(self._compiled),
                "bucketsCompiled": {k: sorted(v)
                                    for k, v in sorted(by_label.items())},
                "sharedJits": sorted(self._jits),
                "compileCount": self.compile_count,
                "compileSeconds": round(self.compile_seconds, 4),
                "inflight": len(self._inflight),
                "failedBuckets": len(self._failed),
            }
        hits, misses = _vals(self._c_hits), _vals(self._c_misses)
        out["dispatchHits"] = hits
        out["dispatchMisses"] = misses
        out["dispatchFallbacks"] = _vals(self._c_fallbacks)
        total = sum(hits.values()) + sum(misses.values())
        out["hitRate"] = (round(sum(hits.values()) / total, 4)
                          if total else None)
        out["pcache"] = costmon.pcache_totals()
        return out

    def clear(self) -> None:
        with self._lock:
            self._compiled.clear()
            self._jits.clear()
            self._failed.clear()

    def shutdown(self, join_timeout_s: float = 15.0) -> None:
        """Quiesce for interpreter exit: wait out in-flight background
        compiles (a daemon thread killed mid-XLA-compile aborts the
        process), then release the held executables (destructing them
        after the jax backend tears down segfaults)."""
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            try:
                t.join(timeout=join_timeout_s)
            except Exception:
                pass
        self.clear()


_registry_lock = threading.Lock()
_registry: Optional[AOTRegistry] = None


def _drop_executables_at_exit():
    # held Compiled objects must be released (and in-flight background
    # compiles joined) BEFORE the jax backend tears down — interpreter-
    # finalization destruction of the module global after the runtime
    # is gone segfaults, and a daemon compile thread killed mid-XLA
    # aborts (both observed on jaxlib 0.4.x CPU at aot_smoke.sh exit).
    # atexit runs pre-finalization, before jax's own handlers unwind.
    try:
        if _registry is not None:
            _registry.shutdown()
    except Exception:
        pass


def get_aot() -> AOTRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = AOTRegistry()
                try:
                    from predictionio_tpu.obs import get_registry
                    get_registry().gauge_func(
                        "pio_aot_executables_resident",
                        "AOT-compiled executables currently held by "
                        "the process registry",
                        lambda: float(len(_registry._compiled))
                        if _registry is not None else 0.0)
                except Exception:
                    logger.debug("aot gauge unavailable", exc_info=True)
                import atexit
                atexit.register(_drop_executables_at_exit)
    return _registry


def shared_jit(key: str, impl: Callable, **jit_kwargs):
    """Module-level convenience for :meth:`AOTRegistry.shared_jit`."""
    return get_aot().shared_jit(key, impl, **jit_kwargs)


def sharded_aval(shape, dtype, *axes, mesh=None):
    """A ``ShapeDtypeStruct`` carrying a ``NamedSharding`` over the
    (current) mesh — the sharding-aware aval sharded spec builders
    lower with, so the bucket ladder and swap-time warmup cover the
    model-sharded serve executables exactly like the replicated ones
    (an aval without a sharding would lower a single-device program
    and the held executable would reject every sharded argument).
    ``axes`` is the per-dim mesh axis name (or None), e.g.
    ``sharded_aval((i, r), np.float32, "model", None)``."""
    import jax
    from predictionio_tpu.parallel.mesh import current_mesh
    ctx = mesh or current_mesh()
    return jax.ShapeDtypeStruct(
        shape, dtype,
        sharding=jax.sharding.NamedSharding(
            ctx.mesh, jax.sharding.PartitionSpec(*axes)))


def warm_enabled() -> bool:
    """Deploy/swap-time warming can be disabled separately from AOT
    dispatch (``PIO_AOT_WARM=off``): dispatch + background adoption
    keep working, but model changes stop pre-compiling the bucket
    ladder — the hermetic test suite uses this (dozens of server
    fixtures would each pay the ladder), production never should."""
    return os.environ.get("PIO_AOT_WARM", "").lower() not in (
        "off", "0", "false", "no")


def warm_models(algorithms, models, batch_hint: int = 16,
                background: bool = False) -> Dict[str, Any]:
    """Warm the serving executables for a (algorithms, models) pair —
    the deploy/hot-swap/canary hook. Each algorithm exposing
    ``aot_warm_specs(model, batch_hint)`` contributes (label, dims)
    rows; everything is fail-soft (a warm failure must never block a
    swap — the fallback path still serves)."""
    if not aot_enabled() or not warm_enabled():
        return {"compiled": 0, "skipped": 0, "wallS": 0.0,
                "disabled": True}
    from predictionio_tpu.compile.cache import enable_persistent_cache
    enable_persistent_cache()
    specs: List[Tuple[str, Dict[str, int]]] = []
    for algo, model in zip(algorithms, models):
        hook = getattr(algo, "aot_warm_specs", None)
        if hook is None:
            continue
        try:
            specs.extend(hook(model, batch_hint))
        except Exception:
            logger.warning("aot_warm_specs failed for %s",
                           type(algo).__name__, exc_info=True)
    out = get_aot().warm(specs, background=background)
    out["specs"] = len(specs)
    return out
