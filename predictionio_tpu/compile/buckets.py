"""Shape-bucket ladder: the sizes the compile plane compiles for.

Every traced program's cost model keys on shapes; every shape that
changes is a recompile. The ladder quantizes the three dims that
actually move in production — vocabulary rows (users/items grow with
traffic), touched-row counts (fold ticks), and query batch sizes — to
next-power-of-two buckets with a floor, so:

- growth INSIDE a bucket changes no traced shape (zero recompiles);
- a promotion (bucket -> 2x) is one predictable compile per
  executable, cheap enough to run in the background before the shape
  is needed (``occupancy`` past ``PROMOTE_AT`` is the trigger);
- the program count per executable is bounded by log2(max size).

Pure host math — no jax imports, safe everywhere.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: smallest vocabulary-row bucket: tiny models all share one program
ROWS_FLOOR = 64
#: smallest batch bucket (a single query is its own class)
BATCH_FLOOR = 1
#: smallest top-k bucket: client-chosen num in 1..16 shares one
#: program (and one deploy-time warm spec); the extra top-k positions
#: are noise next to the scoring matmul
K_FLOOR = 16
#: fraction of a bucket in use at which the next bucket should be
#: pre-compiled in the background (before growth forces it on a tick)
PROMOTE_AT = 0.75


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def bucket_rows(n: int, floor: int = ROWS_FLOOR) -> int:
    """Row-count bucket covering ``n`` (vocab rows, touched rows)."""
    return max(int(floor), _next_pow2(max(int(n), 1)))


def bucket_batch(n: int, floor: int = BATCH_FLOOR) -> int:
    """Query-batch bucket covering ``n``."""
    return max(int(floor), _next_pow2(max(int(n), 1)))


def bucket_rows_sharded(n: int, shards: int,
                        floor: int = ROWS_FLOOR) -> int:
    """Row bucket for a model-axis-sharded table: the pow2 bucket
    rounded up to a multiple of the shard count, so every shard gets
    an equal contiguous row slice (pow2 shard counts divide pow2
    buckets for free; a 3-way mesh axis still gets a legal layout)."""
    b = bucket_rows(n, floor=floor)
    s = max(int(shards), 1)
    return ((b + s - 1) // s) * s


def occupancy(n: int, bucket: int) -> float:
    """How full ``bucket`` is at current size ``n`` (0..1]."""
    return float(n) / float(bucket) if bucket else 1.0


def should_promote(n: int, bucket: int,
                   threshold: float = PROMOTE_AT) -> bool:
    """True when ``n`` is close enough to ``bucket`` that the next
    bucket's executables should compile now, in the background."""
    return occupancy(n, bucket) >= threshold


def next_bucket(bucket: int) -> int:
    return int(bucket) * 2


def bucket_key(dims: Dict[str, int]) -> Tuple[Tuple[str, int], ...]:
    """Canonical hashable key for a bucket-dim dict (sorted items).

    Dims need not all be sizes: flag dims ride the same key — ``s``
    (shard count, sharded vs replicated layout), ``fp`` (positive-
    score filter), and ``p`` (readback pack mode, ISSUE 19: the packed
    variant's single-payload output aval is a different program). Each
    flag value owns its own warmed executables, so flipping a flag at
    runtime never invalidates the other value's buckets."""
    return tuple(sorted((str(k), int(v)) for k, v in dims.items()))


def bucket_label(dims: Dict[str, int]) -> str:
    """Compact metric-label rendering: ``"b16-i2048-u1024"``. Bucket
    combinations are log-bounded per dim, so cardinality stays small."""
    return "-".join(f"{k}{v}" for k, v in bucket_key(dims))
