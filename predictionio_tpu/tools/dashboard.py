"""Dashboard server: lists evaluation instances + results.

Rebuilds the reference's Dashboard
(reference: tools/src/main/scala/io/prediction/tools/dashboard/Dashboard.scala:76-138
and the twirl index page): an HTML index of completed evaluation instances
with per-instance result pages in txt/html/json.
"""

from __future__ import annotations

import html as _html
from dataclasses import dataclass

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.utils.http import (HttpServer, Request, Response,
                                         Router)


@dataclass
class DashboardConfig:
    ip: str = "127.0.0.1"
    port: int = 9000


class Dashboard:
    def __init__(self, config: DashboardConfig = DashboardConfig()):
        self.config = config
        self.router = self._build_router()
        self.server = None

    def _index(self, req: Request) -> Response:
        instances = Storage.get_meta_data_evaluation_instances() \
            .get_completed()
        rows = []
        for i in instances:
            rows.append(
                f"<tr><td>{i.id}</td>"
                f"<td>{_html.escape(i.evaluation_class)}</td>"
                f"<td>{_html.escape(i.engine_params_generator_class)}</td>"
                f"<td>{i.start_time}</td><td>{i.end_time}</td>"
                f"<td><a href='/engine_instances/{i.id}/evaluator_results."
                f"txt'>txt</a> "
                f"<a href='/engine_instances/{i.id}/evaluator_results."
                f"html'>HTML</a> "
                f"<a href='/engine_instances/{i.id}/evaluator_results."
                f"json'>JSON</a></td></tr>")
        page = ("<html><head><title>PredictionIO Dashboard</title></head>"
                "<body><h1>Completed Evaluations</h1><table border=1>"
                "<tr><th>ID</th><th>Evaluation</th><th>Generator</th>"
                "<th>Start</th><th>End</th><th>Results</th></tr>"
                + "".join(rows) + "</table></body></html>")
        return Response(200, page, content_type="text/html; charset=UTF-8")

    def _result(self, req: Request) -> Response:
        instance_id, fmt = req.path_args
        i = Storage.get_meta_data_evaluation_instances().get(instance_id)
        if i is None or i.status != "EVALCOMPLETED":
            return Response(404, {"message": "Not Found"})
        if fmt == "txt":
            return Response(200, i.evaluator_results,
                            content_type="text/plain; charset=UTF-8")
        if fmt == "html":
            return Response(200, i.evaluator_results_html,
                            content_type="text/html; charset=UTF-8")
        return Response(200, i.evaluator_results_json)

    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self._index)
        r.add("GET", "/engine_instances/<id>/evaluator_results.<fmt>",
              self._result)
        return r

    def start(self, background: bool = True) -> "Dashboard":
        srv = HttpServer(self.router, self.config.ip, self.config.port)
        self.server = srv
        srv.start(background=background)
        # read the port from the local: a concurrent stop() (signal
        # handler) may null self.server the instant serve_forever returns
        self.config.port = srv.port
        return self

    def stop(self):
        if self.server:
            self.server.stop()
            self.server = None
