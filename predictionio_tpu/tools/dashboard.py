"""Dashboard server: lists evaluation instances + results.

Rebuilds the reference's Dashboard
(reference: tools/src/main/scala/io/prediction/tools/dashboard/Dashboard.scala:76-138
and the twirl index page): an HTML index of completed evaluation instances
with per-instance result pages in txt/html/json.

ISSUE 2 adds ``/telemetry``: a compact live view of the stack — the
engine and event servers' ``/stats.json`` (fetched over HTTP, so the
dashboard works from its own process) plus this process's own registry
snapshot and recent traces — and ``/metrics`` for the dashboard process
itself.
"""

from __future__ import annotations

import html as _html
from dataclasses import dataclass

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import get_registry
from predictionio_tpu.utils.http import (HttpServer, Request, Response,
                                         Router)


@dataclass
class DashboardConfig:
    ip: str = "127.0.0.1"
    port: int = 9000
    # the stack servers the /telemetry view polls
    engine_url: str = "http://127.0.0.1:8000"
    event_server_url: str = "http://127.0.0.1:7070"


class Dashboard:
    def __init__(self, config: DashboardConfig = DashboardConfig()):
        self.config = config
        from predictionio_tpu.obs import jaxmon
        jaxmon.install()   # /metrics carries the JAX runtime families
        self.router = self._build_router()
        self.server = None
        self._fleet_id = None   # set by start()'s on_bound (ISSUE 13)

    def _index(self, req: Request) -> Response:
        instances = Storage.get_meta_data_evaluation_instances() \
            .get_completed()
        rows = []
        for i in instances:
            rows.append(
                f"<tr><td>{i.id}</td>"
                f"<td>{_html.escape(i.evaluation_class)}</td>"
                f"<td>{_html.escape(i.engine_params_generator_class)}</td>"
                f"<td>{i.start_time}</td><td>{i.end_time}</td>"
                f"<td><a href='/engine_instances/{i.id}/evaluator_results."
                f"txt'>txt</a> "
                f"<a href='/engine_instances/{i.id}/evaluator_results."
                f"html'>HTML</a> "
                f"<a href='/engine_instances/{i.id}/evaluator_results."
                f"json'>JSON</a></td></tr>")
        page = ("<html><head><title>PredictionIO Dashboard</title></head>"
                "<body><h1>Completed Evaluations</h1><table border=1>"
                "<tr><th>ID</th><th>Evaluation</th><th>Generator</th>"
                "<th>Start</th><th>End</th><th>Results</th></tr>"
                + "".join(rows) + "</table></body></html>")
        return Response(200, page, content_type="text/html; charset=UTF-8")

    def _result(self, req: Request) -> Response:
        instance_id, fmt = req.path_args
        i = Storage.get_meta_data_evaluation_instances().get(instance_id)
        if i is None or i.status != "EVALCOMPLETED":
            return Response(404, {"message": "Not Found"})
        if fmt == "txt":
            return Response(200, i.evaluator_results,
                            content_type="text/plain; charset=UTF-8")
        if fmt == "html":
            return Response(200, i.evaluator_results_html,
                            content_type="text/html; charset=UTF-8")
        return Response(200, i.evaluator_results_json)

    # -- ISSUE 2: the compact live telemetry view ---------------------------
    @staticmethod
    def _fetch_json(url: str):
        from predictionio_tpu.utils.http import fetch_json
        return fetch_json(url)

    @staticmethod
    def _kv_rows(d: dict, keys) -> str:
        rows = []
        for k in keys:
            if k in d:
                v = d[k]
                if isinstance(v, float):
                    v = f"{v:.6g}"
                rows.append(f"<tr><td>{_html.escape(str(k))}</td>"
                            f"<td>{_html.escape(str(v))}</td></tr>")
        return "".join(rows)

    @staticmethod
    def _hist_row(name: str, h: dict) -> str:
        if not isinstance(h, dict) or "count" not in h:
            return ""
        cells = "".join(
            f"<td>{h.get(k, 0.0) * 1000:.3f}</td>"
            for k in ("p50", "p95", "p99"))
        return (f"<tr><td>{_html.escape(name)}</td>"
                f"<td>{h['count']}</td>{cells}</tr>")

    def _telemetry(self, req: Request) -> Response:
        """GET /telemetry — one page: per-server counters and latency
        percentiles, slowest recent traces, this process's registry."""
        cfg = self.config
        engine = self._fetch_json(f"{cfg.engine_url}/stats.json")
        events = self._fetch_json(f"{cfg.event_server_url}/stats.json")
        traces = self._fetch_json(
            f"{cfg.engine_url}/traces.json?n=10&sort=slowest"
        ).get("traces", [])

        eng_rows = self._kv_rows(engine, (
            "error", "requestCount", "avgServingSec", "avgPredictSec",
            "modelSwaps", "foldIns", "foldInEvents", "modelVersion"))
        hist_rows = "".join(
            self._hist_row(name, engine.get(name, {}))
            for name in ("queryLatency", "batchWait"))
        ev_rows = self._kv_rows(events, ("error",))
        cur = events.get("currentWindow")
        if isinstance(cur, dict):
            ev_rows += self._kv_rows(cur, ("count",))
            for k, v in sorted(cur.get("byEvent", {}).items()):
                ev_rows += (f"<tr><td>event {_html.escape(k)}</td>"
                            f"<td>{v}</td></tr>")
        trace_rows = "".join(
            f"<tr><td>{_html.escape(t.get('kind', '?'))}</td>"
            f"<td>{_html.escape(t.get('traceId', ''))}</td>"
            f"<td>{t.get('durationMs')}</td>"
            f"<td>{len(t.get('links', []))}</td></tr>"
            for t in traces if isinstance(t, dict))
        # slow-query waterfalls (ISSUE 11): the engine server's ring,
        # each row a stage breakdown whose trace id is replayable via
        # /traces.json?trace_id=
        slow = self._fetch_json(
            f"{cfg.engine_url}/slow.json?n=10").get("slow", [])
        slow_rows = ""
        for e in slow:
            if not isinstance(e, dict):
                continue
            waterfall = " → ".join(
                "{} {}ms".format(st.get("stage"), st.get("ms"))
                for st in e.get("stages", ()))
            slow_rows += (
                f"<tr><td>{_html.escape(str(e.get('traceId', '')))}"
                f"</td><td>{_html.escape(str(e.get('tenant') or '-'))}"
                f"</td><td>{e.get('totalMs')}</td>"
                f"<td>{_html.escape(waterfall)}</td></tr>")
        reg_rows = ""
        for name, val in sorted(get_registry().snapshot().items()):
            if isinstance(val, dict) and "count" in val:
                reg_rows += self._hist_row(name, val)
            elif isinstance(val, (int, float)):
                reg_rows += (f"<tr><td>{_html.escape(name)}</td>"
                             f"<td>{val:g}</td></tr>")
        page = f"""<html><head><title>pio-tpu telemetry</title>
<meta http-equiv="refresh" content="5"></head><body>
<h1>Telemetry</h1>
<h2>Engine server ({_html.escape(cfg.engine_url)})</h2>
<table border=1>{eng_rows}</table>
<table border=1><tr><th>histogram</th><th>count</th><th>p50 ms</th>
<th>p95 ms</th><th>p99 ms</th></tr>{hist_rows}</table>
<h2>Event server ({_html.escape(cfg.event_server_url)})</h2>
<table border=1>{ev_rows}</table>
<h2>Slowest recent traces</h2>
<table border=1><tr><th>kind</th><th>trace</th><th>ms</th>
<th>links</th></tr>{trace_rows}</table>
<h2>Slow-query waterfalls</h2>
<table border=1><tr><th>trace</th><th>tenant</th><th>total ms</th>
<th>stages</th></tr>{slow_rows}</table>
<h2>This process's registry</h2>
<table border=1>{reg_rows}</table>
</body></html>"""
        return Response(200, page, content_type="text/html; charset=UTF-8")

    def _metrics(self, req: Request) -> Response:
        from predictionio_tpu.utils.prometheus import CONTENT_TYPE
        return Response(200, get_registry().render(),
                        content_type=CONTENT_TYPE)

    def _traces(self, req: Request) -> Response:
        from predictionio_tpu.obs import traces_response
        return Response(200, traces_response(req.params))

    def _flight(self, req: Request) -> Response:
        """GET /flight.json — the dashboard process's flight ring
        (ISSUE 6); per-server rings live on the servers themselves."""
        from predictionio_tpu.obs import flight_response
        return Response(200, flight_response(req.params))

    # -- fleet federation (ISSUE 13): the dashboard is a full fleet
    # citizen — it registers a member record and serves the same
    # /fleet/* federation surface as both servers, so an operator can
    # point Prometheus or `pio fleet` at whichever process is exposed.
    def _fleet_status(self, req: Request) -> Response:
        from predictionio_tpu.obs import fleet
        return Response(200, fleet.fleet_status_response(req.params))

    def _fleet_health(self, req: Request) -> Response:
        from predictionio_tpu.obs import fleet
        return Response(200, fleet.fleet_health_response(req.params))

    def _fleet_metrics(self, req: Request) -> Response:
        from predictionio_tpu.obs import fleet
        from predictionio_tpu.utils.prometheus import CONTENT_TYPE
        return Response(200, fleet.fleet_metrics_response(req.params),
                        content_type=CONTENT_TYPE)

    def _fleet_traces(self, req: Request) -> Response:
        from predictionio_tpu.obs import fleet
        return Response(200, fleet.fleet_traces_response(req.params))

    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self._index)
        r.add("GET", "/telemetry", self._telemetry)
        r.add("GET", "/metrics", self._metrics)
        r.add("GET", "/traces.json", self._traces)
        r.add("GET", "/flight.json", self._flight)
        r.add("GET", "/fleet/status.json", self._fleet_status)
        r.add("GET", "/fleet/health.json", self._fleet_health)
        r.add("GET", "/fleet/metrics", self._fleet_metrics)
        r.add("GET", "/fleet/traces.json", self._fleet_traces)
        r.add("GET", "/engine_instances/<id>/evaluator_results.<fmt>",
              self._result)
        return r

    def start(self, background: bool = True) -> "Dashboard":
        from predictionio_tpu.obs import fleet
        srv = HttpServer(self.router, self.config.ip, self.config.port)
        self.server = srv

        def _bound(s):
            # post-bind / pre-serve (the foreground path never returns)
            self.config.port = s.port
            self._fleet_id = fleet.register_member(
                "dashboard", port=s.port, host=self.config.ip)

        srv.on_bound = _bound
        srv.start(background=background)
        return self

    def stop(self):
        from predictionio_tpu.obs import fleet
        fleet.deregister_member(getattr(self, "_fleet_id", None))
        self._fleet_id = None
        if self.server:
            self.server.stop()
            self.server = None
