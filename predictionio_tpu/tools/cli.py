"""The `pio` command-line interface.

Rebuilds the reference's Console
(reference: tools/src/main/scala/io/prediction/tools/console/Console.scala:186-651):
same verbs, argparse instead of scopt, no spark-submit — train/eval/deploy
run in-process on the device mesh (Runner.scala's role collapses into a
plain function call; multi-host launch is env-driven via
parallel.mesh.init_distributed).

Verbs: version, status, build, train, eval, deploy, undeploy, eventserver,
dashboard, adminserver, app {new,list,show,delete,data-delete,channel-new,
channel-delete}, accesskey {new,list,delete}, template {list,get}, export,
import, trim, run; beyond-parity: update, servers, snapshot, faults,
rollback, spill {status,peek,requeue}.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import urllib.request
from typing import List, Optional

logger = logging.getLogger(__name__)


def _print(s=""):
    print(s)


# ---------------------------------------------------------------------------
# command implementations
# ---------------------------------------------------------------------------

def cmd_version(args) -> int:
    import predictionio_tpu
    _print(predictionio_tpu.__version__)
    return 0


def cmd_status(args) -> int:
    """(Console.scala:1033 status — verify storage + mesh).
    ``--telemetry`` (ISSUE 2) additionally polls the running servers'
    /stats.json + /traces.json and prints the compact operator view:
    counters, registry-derived latency percentiles, fold activity, and
    the slowest recent traces."""
    from predictionio_tpu.data.storage.registry import Storage
    _print("Inspecting storage backend connections...")
    results = Storage.verify_all_data_objects()
    for repo, ok in results.items():
        _print(f"  {repo}: {'OK' if ok else 'FAILED'} "
               f"({Storage.config_summary().get(repo, '?')})")
    _print("Inspecting device mesh...")
    try:
        import jax
        devices = jax.devices()
        _print(f"  {len(devices)} device(s): "
               f"{[d.platform + ':' + str(d.id) for d in devices]}")
    except Exception as e:
        _print(f"  device init failed: {e}")
        return 1
    if getattr(args, "telemetry", False):
        _print_telemetry(args)
    if getattr(args, "slo", False):
        _print_slo(args)
    if all(results.values()):
        _print("Your system is all ready to go.")
        return 0
    return 1


def _status_targets(args):
    """(name, base_url) pairs `pio status --telemetry/--slo` poll:
    ``--url`` points the probes at ONE explicit fleet member (ISSUE 13
    satellite — any process on any host, not just the local default
    ports); the default stays the local engine + event server pair."""
    url = getattr(args, "url", None)
    if url:
        return [("member", url.rstrip("/"))]
    ip = getattr(args, "ip", None) or "127.0.0.1"
    return [
        ("engine", f"http://{ip}:{getattr(args, 'engine_port', 8000)}"),
        ("events", f"http://{ip}:"
                   f"{getattr(args, 'event_server_port', 7070)}"),
    ]


def _print_slo(args) -> None:
    """`pio status --slo` (ISSUE 6): each server's /health.json as a
    compact burn-rate table."""
    from predictionio_tpu.utils.http import fetch_json as _fetch_json
    targets = _status_targets(args)
    for name, base in targets:
        _print(f"{name.capitalize()} server SLOs...")
        h = _fetch_json(f"{base}/health.json")
        if "error" in h:
            _print(f"  unreachable: {h['error']}")
            continue
        _print(f"  overall: {h.get('status')}")
        for s in h.get("slo", ()):
            bits = [f"  {s.get('name', '?'):20s} {s.get('status'):8s}"]
            if s.get("burnFast") is not None:
                bits.append(f"burn fast/slow="
                            f"{s['burnFast']}/{s.get('burnSlow')}")
            if s.get("rateFast") is not None:
                bits.append(f"rate={s['rateFast']}/s "
                            f"(min {s.get('minRate')})")
            if s.get("value") is not None:
                bits.append(f"value={round(s['value'], 3)} "
                            f"(max {s.get('maxValue')})")
            if s.get("eventsFast") is not None:
                bits.append(f"events fast/slow={s['eventsFast']}/"
                            f"{s.get('eventsSlow')} "
                            f"(budget {s.get('budget')})")
            _print(" ".join(bits))


def _print_hist(name: str, h) -> None:
    if not isinstance(h, dict) or not h.get("count"):
        return
    _print(f"    {name}: n={h['count']} "
           f"p50={h.get('p50', 0) * 1000:.3f}ms "
           f"p95={h.get('p95', 0) * 1000:.3f}ms "
           f"p99={h.get('p99', 0) * 1000:.3f}ms")


def _print_telemetry(args) -> None:
    from predictionio_tpu.utils.http import fetch_json as _fetch_json
    targets = dict(_status_targets(args))
    engine = targets.get("engine") or targets.get("member")
    events = targets.get("events") or targets.get("member")

    _print("Engine server telemetry...")
    st = _fetch_json(f"{engine}/stats.json")
    if "error" in st:
        _print(f"  unreachable: {st['error']}")
    else:
        _print(f"  requests={st.get('requestCount')} "
               f"avgServing={st.get('avgServingSec', 0):.6f}s "
               f"avgPredict={st.get('avgPredictSec', 0):.6f}s")
        _print(f"  modelSwaps={st.get('modelSwaps')} "
               f"foldIns={st.get('foldIns')} "
               f"foldInEvents={st.get('foldInEvents')} "
               f"version={st.get('modelVersion')}")
        _print_hist("queryLatency", st.get("queryLatency"))
        _print_hist("batchWait", st.get("batchWait"))
        # compile plane (ISSUE 9): AOT registry + persistent-cache view
        aot = st.get("aot") or {}
        if aot:
            _print(f"  aot: resident={aot.get('executablesResident')} "
                   f"hitRate={aot.get('hitRate')} "
                   f"compiles={aot.get('compileCount')} "
                   f"({aot.get('compileSeconds')}s) "
                   f"sharedJits={len(aot.get('sharedJits', []))}")
            for label, bks in sorted(
                    (aot.get("bucketsCompiled") or {}).items()):
                _print(f"    {label}: {len(bks)} bucket(s) "
                       f"[{', '.join(bks[:4])}"
                       f"{', ...' if len(bks) > 4 else ''}]")
        xc = st.get("xlaCache") or {}
        if xc:
            _print(f"  xlaCache: entries={xc.get('entries')} "
                   f"hits={xc.get('hits')} misses={xc.get('misses')} "
                   f"salt={xc.get('salt')}")
        if st.get("swapToFirstQueryMs") is not None:
            _print(f"  swapToFirstQuery="
                   f"{st['swapToFirstQueryMs']:.1f}ms")
    _print("Event server telemetry...")
    ev = _fetch_json(f"{events}/stats.json?accessKey="
                     f"{getattr(args, 'accesskey', '') or ''}")
    if "error" in ev:
        _print(f"  unreachable or no --stats: {ev['error']}")
    else:
        cur = ev.get("currentWindow", {})
        _print(f"  window events={cur.get('count')} "
               f"byEvent={cur.get('byEvent')}")
    _print("Slowest recent traces (engine)...")
    traces = _fetch_json(
        f"{engine}/traces.json?n=5&sort=slowest").get("traces")
    if not traces:
        _print("  none")
    else:
        for t in traces:
            spans = t.get("root", {}).get("children", [])
            stages = ",".join(s.get("name", "?") for s in spans[:6])
            _print(f"  {t.get('kind'):14s} {t.get('durationMs', 0):>10}ms "
                   f"links={len(t.get('links', []))} [{stages}] "
                   f"{t.get('traceId')}")


def cmd_build(args) -> int:
    """Validate engine.json + factory import and register the engine
    manifest (the sbt-compile + RegisterEngine analog — Python engines need
    no compilation; Console.scala:924, RegisterEngine.scala)."""
    from predictionio_tpu.data.storage.base import EngineManifest
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.models import get_engine_factory
    with open(args.engine_json) as f:
        variant = json.load(f)
    factory_name = variant.get("engineFactory")
    if not factory_name:
        _print("engineFactory missing in engine.json")
        return 1
    factory = get_engine_factory(factory_name)
    engine = factory.apply()
    engine.json_to_engine_params(variant)
    manifest = EngineManifest(
        id=variant.get("id", "default"),
        version=str(variant.get("version", "0")),
        name=variant.get("id", factory_name),
        description=variant.get("description"),
        files=(args.engine_json,),
        engine_factory=factory_name)
    Storage.get_meta_data_engine_manifests().insert(manifest)
    _print(f"Engine {factory_name} is valid. Registered manifest "
           f"{manifest.id} {manifest.version}. Build finished successfully.")
    return 0


def cmd_unregister(args) -> int:
    """(Console unregister — remove the engine manifest)"""
    from predictionio_tpu.data.storage.registry import Storage
    with open(args.engine_json) as f:
        variant = json.load(f)
    mid = variant.get("id", "default")
    version = str(variant.get("version", "0"))
    if Storage.get_meta_data_engine_manifests().delete(mid, version):
        _print(f"Unregistered engine {mid} {version}.")
        return 0
    _print(f"Engine {mid} {version} is not registered.")
    return 1


def cmd_train(args) -> int:
    from predictionio_tpu.parallel.mesh import init_distributed
    from predictionio_tpu.workflow import (WorkflowConfig,
                                           create_workflow_main)
    init_distributed()  # no-op unless PIO_COORDINATOR/... are set
    config = WorkflowConfig(
        batch=args.batch or "",
        engine_variant=args.engine_json,
        engine_id=args.engine_id or "default",
        engine_version=args.engine_version or "0",
        engine_factory=args.engine_factory,
        engine_params_key=args.engine_params_key,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
        verbose=args.verbose)
    instance_id = create_workflow_main(config)
    _print(f"Training completed. Engine instance ID: {instance_id}")
    return 0


def cmd_eval(args) -> int:
    from predictionio_tpu.workflow import (WorkflowConfig,
                                           create_workflow_main)
    config = WorkflowConfig(
        batch=args.batch or "",
        engine_variant=args.engine_json,
        evaluation_class=args.evaluation_class,
        engine_params_generator_class=args.engine_params_generator_class)
    instance_id = create_workflow_main(config)
    _print(f"Evaluation completed. Evaluation instance ID: {instance_id}")
    return 0


def _serve_foreground(server, label: str) -> int:
    """Run a server in the foreground, stopping CLEANLY on SIGTERM/SIGINT
    (systemd/k8s stop, operator ^C): the listener stops accepting, the
    engine server's batcher fails any still-queued waiters loudly (no
    stranded request threads), and the mesh coordinator broadcasts the
    worker-release so executor processes exit instead of hanging in a
    collective. The handler fires server.stop() from a helper thread —
    calling shutdown from inside serve_forever's own thread deadlocks.
    (The reference's actor system gets this from its lifecycle; a bare
    HTTP loop has to do it explicitly.)"""
    import os
    import signal
    import threading
    import time

    torn_down = threading.Event()  # set when serve_forever returns

    def stopper():
        # stop() no-ops until the HTTP socket exists (a signal can land
        # during the up-to-3s bind-retry window, e.g. a systemd restart
        # racing the old instance), so retry until the serve loop is
        # actually torn down — observed via torn_down, NOT assumed: a
        # wedged drain or stuck collective must surface as a nonzero
        # exit to systemd/k8s, not masquerade as a clean stop
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                server.stop()
            except Exception:
                pass
            if torn_down.wait(0.5):
                return  # main thread's start() returned; exits 0 there
        if torn_down.is_set():
            return  # teardown landed exactly at the deadline — still clean
        _print(f"{label}: shutdown did not complete within 15s; "
               "hard-exiting with status 1.")
        os._exit(1)

    def on_sig(signum, frame):
        _print(f"{label}: received signal {signum}, shutting down.")
        threading.Thread(target=stopper, daemon=True).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, on_sig)
    server.start(background=False)
    torn_down.set()
    return 0


def cmd_deploy(args) -> int:
    from predictionio_tpu.parallel.mesh import init_distributed
    from predictionio_tpu.serving import EngineServer, ServerConfig
    init_distributed()  # no-op unless PIO_COORDINATOR/... are set
    import jax
    is_primary = jax.process_index() == 0
    # undeploy a stale server occupying the target port first, as the
    # reference MasterActor does (CreateServer.scala:288-310) — primary
    # only: mesh workers own no port, and probing from every process
    # could kill a peer's live server
    if is_primary:
        try:
            stop_ip = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
            req = urllib.request.Request(
                f"http://{stop_ip}:{args.port}/stop", method="POST",
                data=b"")
            urllib.request.urlopen(req, timeout=3).read()
            _print(f"Undeployed a stale engine server on port {args.port}.")
            import time
            time.sleep(1)
        except Exception:
            pass
    config = ServerConfig(
        ip=args.ip, port=args.port,
        engine_instance_id=args.engine_instance_id,
        engine_id=args.engine_id or "default",
        engine_version=args.engine_version or "0",
        engine_variant=args.engine_json,
        feedback=args.feedback,
        event_server_ip=args.event_server_ip,
        event_server_port=args.event_server_port,
        accesskey=args.accesskey or "",
        mesh_broadcast_bytes=args.mesh_broadcast_bytes,
        canary_fraction=args.canary_fraction,
        canary_window_s=args.canary_window)
    server = EngineServer(config)
    server.load()
    if server.coordinator is not None and not server.coordinator.is_primary:
        # non-zero process of a multi-process mesh: no HTTP frontend —
        # mirror the primary's SPMD predict for every broadcast query
        # (the executor role; CreateServer.scala:490-641)
        _print("Mesh serve worker: mirroring the primary's query path.")
        server.serve_mesh_worker()
        return 0
    _print(f"Engine is deployed and running. Engine API is live at "
           f"http://{config.ip}:{config.port}.")
    return _serve_foreground(server, "engine server")


def cmd_update(args) -> int:
    """`pio update [--follow]` — attach the delta-training scheduler to a
    deployed engine (ISSUE 1 L6): tail the event store, fold fresh events
    into the served model, publish each folded version through the
    model-version registry, and POST /reload so the deployed server
    hot-swaps it. One-shot by default (a single forced tick); --follow
    loops until SIGINT."""
    import json as _json
    import time
    from predictionio_tpu.online import (DeltaTrainingScheduler,
                                         ModelVersionRegistry,
                                         SchedulerConfig)
    from predictionio_tpu.serving import EngineServer, ServerConfig

    # resolve engine + latest model exactly like deploy does, without
    # starting an HTTP frontend (EngineServer is the loader)
    loader = EngineServer(ServerConfig(
        ip="127.0.0.1", port=0,
        engine_id=args.engine_id or "default",
        engine_version=args.engine_version or "0",
        engine_variant=args.engine_json,
        micro_batch=0))
    loader.load()
    _, ds_params = loader.engine_params.data_source_params
    app_name = args.app_name or getattr(ds_params, "app_name", None)
    if not app_name:
        _print("No app name: pass --app-name or set it in the variant's "
               "datasource params.")
        return 1
    config = SchedulerConfig(
        app_name=app_name,
        channel_name=getattr(ds_params, "channel_name", None),
        max_deltas=args.max_deltas,
        max_staleness_s=args.max_staleness,
        drift_ratio=args.drift_ratio,
        poll_interval_s=args.interval)
    reload_url = (f"http://{args.engine_ip}:{args.engine_port}/reload"
                  if args.engine_port else None)
    sched = DeltaTrainingScheduler(
        engine=loader.engine, engine_params=loader.engine_params,
        instance=loader.engine_instance, algorithms=loader.algorithms,
        models=loader.models, config=config,
        registry=ModelVersionRegistry(), reload_url=reload_url)
    if not args.follow:
        report = sched.tick(force=True)
        _print(_json.dumps(report or {"message": "no fresh events"}))
        return 0
    _print(f"Following app {app_name!r} (fold at {config.max_deltas} "
           f"deltas or {config.max_staleness_s:g}s staleness; ^C stops).")
    import logging as _logging
    # a following scheduler is a fleet member (ISSUE 13): its liveness
    # shows in `pio fleet status`, guards its flight series from GC,
    # and puts it on incident bundles' member roster
    from predictionio_tpu.obs import fleet as _fleet
    fleet_id = _fleet.register_member("scheduler")
    try:
        while True:
            try:
                report = sched.tick()
            except Exception:
                # transient tick failure (storage hiccup, solve error):
                # fold_in already restored its deltas for retry — the
                # follower must keep following, not die with a traceback
                _logging.getLogger(__name__).exception(
                    "update tick failed; retrying next interval")
                report = None
            if report:
                _print(_json.dumps(report))
            if sched.retrain_requested:
                _print("Drift bound exceeded — run `pio train` + "
                       "redeploy, then restart `pio update --follow`.")
                return 2
            time.sleep(config.poll_interval_s)
    except KeyboardInterrupt:
        _print("Stopped.")
        _print(_json.dumps(sched.stats()))
        return 0
    finally:
        _fleet.deregister_member(fleet_id)


def cmd_undeploy(args) -> int:
    """(Console undeploy — POST /stop to the deployed server)"""
    url = f"http://{args.ip}:{args.port}/stop"
    try:
        req = urllib.request.Request(url, method="POST", data=b"")
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
        _print(f"Undeployed engine server at {args.ip}:{args.port}.")
        return 0
    except Exception as e:
        _print(f"Undeploy failed: {e}")
        return 1


def cmd_eventserver(args) -> int:
    from predictionio_tpu.data.api.event_server import (EventServer,
                                                        EventServerConfig)
    server = EventServer(EventServerConfig(ip=args.ip, port=args.port,
                                           stats=args.stats,
                                           max_batch=args.max_batch))
    _print(f"Event Server is listening on http://{args.ip}:{args.port}")
    return _serve_foreground(server, "event server")


def cmd_dashboard(args) -> int:
    from predictionio_tpu.tools.dashboard import Dashboard, DashboardConfig
    server = Dashboard(DashboardConfig(
        ip=args.ip, port=args.port,
        engine_url=args.engine_url,
        event_server_url=args.event_server_url))
    _print(f"Dashboard is listening on http://{args.ip}:{args.port}")
    return _serve_foreground(server, "dashboard")


def cmd_adminserver(args) -> int:
    from predictionio_tpu.tools.admin import AdminServer, AdminServerConfig
    server = AdminServer(AdminServerConfig(ip=args.ip, port=args.port))
    _print(f"Admin server is listening on http://{args.ip}:{args.port}")
    return _serve_foreground(server, "admin server")


def cmd_app(args) -> int:
    from predictionio_tpu.tools import app_commands as ac

    def show(desc):
        _print(f"    App Name: {desc.app.name}")
        _print(f"      App ID: {desc.app.id}")
        _print(f" Description: {desc.app.description or ''}")
        for k in desc.access_keys:
            events = ",".join(k.events) if k.events else "(all)"
            _print(f"  Access Key: {k.key} | {events}")
        for c in desc.channels:
            _print(f"     Channel: {c.name} (id {c.id})")

    try:
        if args.app_command == "new":
            desc = ac.app_new(args.name, app_id=args.id or 0,
                              description=args.description,
                              access_key=args.access_key or "")
            _print("Created a new app:")
            show(desc)
        elif args.app_command == "list":
            for desc in ac.app_list():
                keys = ", ".join(k.key for k in desc.access_keys)
                _print(f"{desc.app.id:4d} | {desc.app.name} | {keys}")
        elif args.app_command == "show":
            show(ac.app_show(args.name))
        elif args.app_command == "delete":
            if not args.force and not _confirm(
                    f"Delete app {args.name} and all its data?"):
                return 1
            ac.app_delete(args.name)
            _print(f"Deleted app {args.name}.")
        elif args.app_command == "data-delete":
            if not args.force and not _confirm(
                    f"Delete data of app {args.name}?"):
                return 1
            ac.app_data_delete(args.name, channel=args.channel,
                               delete_all=args.all)
            _print(f"Deleted data of app {args.name}.")
        elif args.app_command == "channel-new":
            c = ac.channel_new(args.name, args.channel)
            _print(f"Created channel {c.name} (id {c.id}) for app "
                   f"{args.name}.")
        elif args.app_command == "channel-delete":
            if not args.force and not _confirm(
                    f"Delete channel {args.channel} of app {args.name}?"):
                return 1
            ac.channel_delete(args.name, args.channel)
            _print(f"Deleted channel {args.channel}.")
        return 0
    except ac.AppCommandError as e:
        _print(str(e))
        return 1


def cmd_accesskey(args) -> int:
    from predictionio_tpu.tools import app_commands as ac
    try:
        if args.accesskey_command == "new":
            events = args.event or []
            k = ac.accesskey_new(args.app_name, key=args.key or "",
                                 events=events)
            _print(f"Created new access key: {k.key}")
        elif args.accesskey_command == "list":
            for k in ac.accesskey_list(args.app_name):
                events = ",".join(k.events) if k.events else "(all)"
                _print(f"{k.key} | app {k.appid} | {events}")
        elif args.accesskey_command == "delete":
            ac.accesskey_delete(args.key)
            _print(f"Deleted access key {args.key}.")
        return 0
    except ac.AppCommandError as e:
        _print(str(e))
        return 1


def cmd_template(args) -> int:
    """Template gallery: built-ins + an optional URI-addressed index
    (the reference's remote gallery mechanism, Template.scala:130-416;
    --gallery or PIO_TEMPLATE_GALLERY points at <root>/index.json)."""
    from predictionio_tpu.data.storage.registry import StorageError
    from predictionio_tpu.tools.templates import (GalleryError,
                                                  get_template,
                                                  list_templates)
    try:
        if args.template_command == "list":
            for name, desc in list_templates(gallery=args.gallery):
                _print(f"  {name:28s} {desc}")
            return 0
        return get_template(args.name, args.directory,
                            gallery=args.gallery)
    except (GalleryError, StorageError) as e:
        # StorageError: unregistered URI scheme from the adapter registry
        _print(f"Template gallery error: {e}")
        return 1


def cmd_export(args) -> int:
    from predictionio_tpu.tools.export_import import (
        export_events, export_events_parquet)
    if getattr(args, "format", "json") == "parquet":
        n = export_events_parquet(args.appid, args.output,
                                  channel_id=args.channelid)
    else:
        n = export_events(args.appid, args.output,
                          channel_id=args.channelid)
    _print(f"Exported {n} events to {args.output}.")
    return 0


def cmd_import(args) -> int:
    from predictionio_tpu.tools.export_import import (
        import_events, import_events_parquet, import_movielens)
    fmt = getattr(args, "format", "events")
    if fmt == "movielens":
        n = import_movielens(args.appid, args.input,
                             channel_id=args.channelid)
    elif fmt == "parquet":
        n = import_events_parquet(args.appid, args.input,
                                  channel_id=args.channelid)
    else:
        n = import_events(args.appid, args.input,
                          channel_id=args.channelid)
    _print(f"Imported {n} events.")
    return 0


def cmd_trim(args) -> int:
    """Copy a time window of events into a fresh app (the trim-app
    workflow: keep only a recent window under a new app id)."""
    from predictionio_tpu.data.event import parse_event_time
    from predictionio_tpu.tools.export_import import trim_events
    try:
        n = trim_events(
            args.src_appid, args.dst_appid,
            start_time=(parse_event_time(args.start)
                        if args.start else None),
            until_time=(parse_event_time(args.until)
                        if args.until else None),
            src_channel_id=args.src_channelid,
            dst_channel_id=args.dst_channelid)
    except ValueError as e:
        _print(f"Error: {e}")
        return 1
    _print(f"Trimmed {n} events from app {args.src_appid} into app "
           f"{args.dst_appid}.")
    return 0


def _engine_mesh_note(ip: str, port: int) -> str:
    """One-glance mesh-coordinator health for the `pio servers` engine
    row (round-4 verdict stretch: a poisoned coordinator — broadcast
    failed, every query 503s — was visible only to query traffic; the
    operator's redeploy signal should be explicit)."""
    try:
        with urllib.request.urlopen(
                f"http://{ip}:{port}/stats.json", timeout=3) as resp:
            mesh = json.loads(resp.read()).get("meshCoordinator")
    except Exception:
        return ""
    if not mesh:
        return ""
    if mesh.get("poisoned"):
        return "  MESH POISONED — redeploy"
    return f"  mesh {mesh.get('processes')}p healthy"


def cmd_servers(args) -> int:
    """Probe the stack's service ports and report what's live — the
    operator's one-glance view of the daemons pio-start-all manages
    (plus any deployed engine server)."""
    import urllib.error
    from concurrent.futures import ThreadPoolExecutor

    def probe(name, port):
        """(display row, is_up) — probes run concurrently so a dropped
        host costs one timeout, not four."""
        url = f"http://{args.ip}:{port}/"
        try:
            with urllib.request.urlopen(url, timeout=3) as resp:
                note = ""
                if name == "engine":
                    note = _engine_mesh_note(args.ip, port)
                return (f"  {name:14s} :{port:<6d} UP ({resp.status})"
                        f"{note}", True)
        except urllib.error.HTTPError as e:
            # an HTTP error still means something is listening
            return f"  {name:14s} :{port:<6d} UP ({e.code})", True
        except Exception:
            return f"  {name:14s} :{port:<6d} down", False

    targets = [("eventserver", args.event_server_port),
               ("engine", args.engine_port),
               ("dashboard", args.dashboard_port),
               ("adminserver", args.admin_port)]
    with ThreadPoolExecutor(len(targets)) as ex:
        rows = list(ex.map(lambda t: probe(*t), targets))
    for row, _ in rows:
        _print(row)
    return 0 if any(up for _, up in rows) else 1


def cmd_snapshot(args) -> int:
    """Durability verbs for the nativelog event store: shard files shipped
    to / restored from a URI-addressed blob store (data/storage/
    snapshot.py; the HBase snapshot-export role of the reference's
    replicated default store)."""
    from predictionio_tpu.data.storage import snapshot as S
    from predictionio_tpu.data.storage.registry import StorageError
    try:
        if args.snapshot_command == "create":
            m = S.create_snapshot(args.appid, args.uri, name=args.name,
                                  channel_id=args.channelid)
            total = sum(e["bytes"] for e in m["files"])
            _print(f"Snapshot {m['name']} created: {len(m['files'])} "
                   f"file(s), {total} bytes at {args.uri}.")
        elif args.snapshot_command == "restore":
            m = S.restore_snapshot(args.uri, args.name,
                                   app_id=args.appid,
                                   channel_id=args.channelid,
                                   force=args.force)
            _print(f"Snapshot {m['name']} restored "
                   f"({len(m['files'])} file(s)).")
        else:
            snaps = S.list_snapshots(args.uri)
            if not snaps:
                _print("No snapshots found.")
            for m in snaps:
                total = sum(e["bytes"] for e in m["files"])
                _print(f"  {m['name']}  app={m['app_id']} "
                       f"partitions={m['partitions']} files="
                       f"{len(m['files'])} bytes={total} "
                       f"created={m['created']}")
        return 0
    except (S.SnapshotError, StorageError) as e:
        # StorageError: e.g. an unregistered URI scheme from adapter_for
        _print(f"Snapshot failed: {e}")
        return 1


def cmd_bootstrap(args) -> int:
    """`pio bootstrap <tenant> --snapshot <name> --uri <root>` — stand up
    a new tenant from a snapshot through the bulk data plane (ISSUE 16):
    restore the shard files, train from the restored store via the
    streaming read, catch up the fold tail from the snapshot's creation
    instant, and (with --serve) admit the tenant onto a ServingHost only
    once caught up."""
    import json as _json
    from predictionio_tpu.dataplane import bootstrap_from_snapshot
    from predictionio_tpu.data.storage.registry import StorageError
    from predictionio_tpu.data.storage.snapshot import SnapshotError
    from predictionio_tpu.workflow.create_workflow import (WorkflowConfig,
                                                           _engine_and_params)

    variant, factory_name, engine, engine_params = _engine_and_params(
        WorkflowConfig(engine_variant=args.engine_json,
                       engine_factory=args.engine_factory))
    host = None
    if args.serve:
        from predictionio_tpu.tenancy import HostConfig, ServingHost
        host = ServingHost(HostConfig(ip=args.ip, port=args.port))
    try:
        report = bootstrap_from_snapshot(
            args.tenant, args.uri, args.snapshot,
            engine, engine_params,
            app_name=args.app_name, host=host,
            engine_id=variant.get("id") or None,
            engine_variant=args.engine_json,
            engine_factory=factory_name,
            force=args.force, stream=not args.no_stream,
            start_scheduler=args.serve)
    except (SnapshotError, StorageError, ValueError) as e:
        _print(f"Bootstrap failed: {e}")
        if host is not None:
            host.stop()
        return 1
    _print(_json.dumps(report.to_dict(), default=str))
    if host is None:
        return 0
    _print(f"Tenant {args.tenant!r} admitted; serving host live at "
           f"http://{args.ip}:{args.port}.")
    return _serve_foreground(host, "serving host")


def cmd_run(args) -> int:
    """(Console run — execute a main class/module in the pio environment)"""
    import runpy
    sys.argv = [args.main_py] + (args.args or [])
    runpy.run_path(args.main_py, run_name="__main__")
    return 0


def cmd_faults(args) -> int:
    """Chaos-harness control (ISSUE 3): parse/validate a PIO_FAULTS
    spec, show what is active, and preview the seeded decision stream —
    the operator's dry run before pointing chaos at a live stack."""
    import os as _os

    from predictionio_tpu.resilience.faults import (ENV_VAR, FaultInjector,
                                                    FaultSpec, InjectedFault)
    spec_s = args.spec or _os.environ.get(ENV_VAR, "")
    if not spec_s.strip():
        _print(f"No fault spec: set {ENV_VAR} or pass --spec.")
        _print("Syntax: target:key=value[,key=value][;target:...]")
        _print("  e.g. 'storage.write:error=0.3,seed=42'")
        return 0
    try:
        spec = FaultSpec.parse(spec_s)
    except ValueError as e:
        _print(f"Invalid fault spec: {e}")
        return 1
    _print(f"Fault spec OK (seed={spec.seed if spec.seed is not None else 0}):")
    for target, rule in sorted(spec.rules.items()):
        bits = []
        if rule.error:
            bits.append(f"error={rule.error:g}")
        if rule.partition:
            bits.append(f"partition={rule.partition:g}")
        if rule.latency_ms:
            rate = 1.0 if rule.latency_rate is None else rule.latency_rate
            bits.append(f"latency={rule.latency_ms:g}ms@{rate:g}")
        if rule.corrupt:
            bits.append(f"corrupt={rule.corrupt:g}")
        _print(f"  {target:16s} {', '.join(bits) or '(no-op)'}")
    if args.preview:
        inj = FaultInjector(spec, sleep=lambda s: None)
        _print(f"First {args.preview} seeded decisions for "
               f"{args.target!r}:")
        for i in range(args.preview):
            try:
                inj.before(args.target)
                _print(f"  {i:3d}  ok")
            except InjectedFault:
                _print(f"  {i:3d}  ERROR (injected)")
            except ConnectionError:
                _print(f"  {i:3d}  PARTITION (injected)")
    active = _os.environ.get(ENV_VAR, "").strip()
    _print(f"{ENV_VAR} is "
           + (f"ACTIVE in this environment: {active}" if active
              else "not set (pass it to the server process to arm)"))
    return 0


def cmd_rollback(args) -> int:
    """`pio rollback` (ISSUE 5): demote every COMPLETED model version
    newer than the last-known-good pin (or an explicit --to instance)
    to ROLLEDBACK, so deploy//reload resolve the good version again,
    then POST /reload to the running engine server. The durable
    counterpart of the canary watchdog's in-memory rollback."""
    from predictionio_tpu.online import ModelVersionRegistry
    reg = ModelVersionRegistry()
    engine_id = args.engine_id or "default"
    engine_version = args.engine_version or "0"
    try:
        result = reg.rollback_to(engine_id, engine_version,
                                 args.engine_json, target_id=args.to)
    except ValueError as e:
        _print(f"Rollback failed: {e}")
        return 1
    _print(f"Rolled back to instance {result['target']}.")
    for iid in result["demoted"]:
        _print(f"  demoted {iid} -> ROLLEDBACK")
    if not args.engine_port:
        _print("No engine server to reload (--engine-port 0).")
        return 0
    url = f"http://{args.engine_ip}:{args.engine_port}/reload"
    try:
        req = urllib.request.Request(url, method="POST", data=b"")
        urllib.request.urlopen(req, timeout=30).read()
        _print(f"Reloaded engine server at {url}.")
    except Exception as e:
        _print(f"Reload failed ({e}); the server keeps its current "
               "model until it restarts or /reload succeeds.")
        return 1
    return 0


def cmd_incidents(args) -> int:
    """`pio incidents` (ISSUE 6): browse the postmortem bundles the
    diagnostics plane captured under <PIO_FS_BASEDIR>/incidents/ —
    list them, replay one as the lifecycle story it froze (flight
    records in order, trace links, provider states), or export a
    tar.gz for hand-off."""
    import json as _json

    from predictionio_tpu.obs.incidents import IncidentManager
    mgr = IncidentManager(incidents_dir=getattr(args, "dir", None))
    # --url (ISSUE 13 satellite): browse a FLEET MEMBER's bundles over
    # HTTP instead of the local base_dir — the operator box need not
    # share the member's filesystem
    url = (getattr(args, "url", None) or "").rstrip("/")
    sub = args.incidents_command
    if sub == "list":
        if url:
            from predictionio_tpu.utils.http import fetch_json
            body = fetch_json(f"{url}/incidents.json")
            if not isinstance(body, dict) or "incidents" not in body:
                _print(f"Cannot list incidents at {url}: "
                       f"{(body or {}).get('error') or (body or {}).get('message')}")
                return 1
            rows = body["incidents"]
            where = f"{url} ({body.get('incidentsDir')})"
        else:
            rows = mgr.list_incidents()
            where = mgr.incidents_dir()
        if not rows:
            _print(f"No incidents under {where}.")
            return 0
        for r in rows:
            ten = r.get("tenant")
            _print(f"{r['id']:40s} {r.get('kind', '?'):18s} "
                   f"{(ten or '-'):12s} "
                   f"{r.get('capturedAt', '')}  {r.get('reason', '')}")
        return 0
    if sub == "show":
        if url:
            from predictionio_tpu.utils.http import fetch_json
            bundle = fetch_json(f"{url}/incidents/{args.id}.json")
            if not isinstance(bundle, dict) or "id" not in bundle:
                _print(f"Cannot load incident {args.id} from {url}: "
                       f"{(bundle or {}).get('error') or (bundle or {}).get('message')}")
                return 1
        else:
            try:
                bundle = mgr.load(args.id)
            except (OSError, ValueError) as e:
                _print(f"Cannot load incident {args.id}: {e}")
                return 1
        _print(f"Incident {bundle['id']}: {bundle['kind']} — "
               f"{bundle['reason']}")
        _print(f"  captured: {bundle.get('capturedAt')}")
        if bundle.get("tenant"):
            _print(f"  tenant: {bundle['tenant']}")
        for name, state in (bundle.get("providers") or {}).items():
            _print(f"  [{name}] {_json.dumps(state, default=str)}")
        flight = bundle.get("flight") or []
        _print(f"  flight records ({len(flight)}, oldest first):")
        for rec in flight:
            extra = {k: v for k, v in rec.items()
                     if k not in ("seq", "t", "kind", "traceId",
                                  "modelVersion", "metrics")}
            _print(f"    #{rec.get('seq'):>6} {rec.get('kind', '?'):20s}"
                   f" trace={rec.get('traceId', '-'):16s}"
                   f" version={rec.get('modelVersion', '-')} "
                   f"{_json.dumps(extra, default=str) if extra else ''}")
        traces = bundle.get("traceDetail") or []
        if traces:
            _print(f"  traces ({len(traces)}):")
            for t in traces:
                _print(f"    {t.get('kind', '?'):14s} "
                       f"{t.get('traceId')} links={t.get('links')}")
        members = bundle.get("fleet") or []
        if members:
            _print(f"  fleet at capture ({len(members)} member(s)):")
            for m in members:
                _print(f"    {m.get('memberId', '?'):28s} "
                       f"{'ALIVE' if m.get('alive') else 'DEAD':6s}"
                       f" port={m.get('port') or '-'}"
                       + (f" [{m.get('error') or m.get('metricsError')}]"
                          if m.get("error") or m.get("metricsError")
                          else ""))
        return 0
    if sub == "export":
        if url:
            _print("export needs the member's filesystem; run it on "
                   "that host (list/show work over --url).")
            return 1
        try:
            out = mgr.export(args.id, getattr(args, "out", None))
        except (OSError, FileNotFoundError) as e:
            _print(f"Export failed: {e}")
            return 1
        _print(f"Exported incident {args.id} to {out}.")
        return 0
    _print("incidents subcommand must be list|show|export")
    return 1


def cmd_fleet(args) -> int:
    """`pio fleet {status,metrics,traces}` (ISSUE 13): the whole-fleet
    operator surface over the member registry under
    <PIO_FS_BASEDIR>/fleet/ — liveness, one federated {role,pid}-labeled
    metrics scrape, and a trace id stitched across every member's
    process into one waterfall."""
    from predictionio_tpu.obs import fleet as F
    reg = F.FleetRegistry(fleet_dir=getattr(args, "dir", None)) \
        if getattr(args, "dir", None) else F.get_fleet()
    sub = args.fleet_command
    if sub == "status":
        st = F.fleet_status(reg.members(), registry=reg)
        _print(f"Fleet under {st['fleetDir']} "
               f"(heartbeat {st['heartbeatS']:g}s, liveness window "
               f"{st['livenessWindowS']:g}s):")
        if not st["members"]:
            _print("  no members registered (are the servers running "
                   "with this PIO_FS_BASEDIR?)")
            return 1
        for m in st["members"]:
            url = m.get("url") or (F.member_url(m) or "-")
            _print(f"  {m.get('memberId', '?'):28s} "
                   f"{'UP' if m.get('alive') else 'DEAD':5s} "
                   f"pid={m.get('pid')} "
                   f"url={url:<28} "
                   f"beat {m.get('ageS', 0):.1f}s ago"
                   + (f" tenants={','.join(sorted(m['tenants']))}"
                      if m.get("tenants") else ""))
        _print(f"  {st['alive']} alive, {st['dead']} dead")
        return 0 if st["dead"] == 0 else 1
    if sub == "metrics":
        _print(F.federate_metrics(reg.live_members()).rstrip("\n"))
        return 0
    if sub == "traces":
        out = F.fleet_traces(args.id, members=reg.live_members(),
                             limit=args.n)
        for q in out["members"]:
            if not q.get("ok"):
                _print(f"# {q.get('memberId')}: {q.get('error')}")
        if not out["traces"]:
            _print(f"No member holds trace {args.id} (rings rotate; "
                   "capture an incident to freeze one).")
            return 1
        _print(f"Trace {args.id}: {len(out['traces'])} process-local "
               f"trace(s) across pids {out['pids']}")

        def walk(span, depth):
            _print(f"    {'  ' * depth}{span.get('name', '?'):24s} "
                   f"{span.get('durationMs', '?')}ms"
                   + (f" {span['attrs']}" if span.get("attrs") else ""))
            for c in span.get("children", ()):
                walk(c, depth + 1)

        for t in out["traces"]:
            m = t.get("member") or {}
            tag = " <- THE trace" if t.get("traceId") == args.id \
                else f" (links {t.get('links')})"
            _print(f"  [{m.get('role', '?')}:{t.get('pid', '?')}] "
                   f"{t.get('kind'):16s} {t.get('durationMs')}ms "
                   f"{t.get('traceId')}{tag}")
            walk(t.get("root") or {}, 1)
        return 0
    _print("fleet subcommand must be status|metrics|traces")
    return 1


def _default_spill_path() -> str:
    import os as _os
    from predictionio_tpu.data.storage.registry import base_dir
    return _os.path.join(base_dir(), "ingest_spill", "events.wal")


def cmd_spill(args) -> int:
    """`pio spill` (ISSUE 5 satellite): inspect the ingest spill WAL
    and its quarantine sidecar without reading raw files by hand —
    pending counts, peek at the oldest records, requeue quarantined
    ones after fixing their root cause."""
    import json as _json

    from predictionio_tpu.resilience.spill import (iter_pending,
                                                   read_quarantine,
                                                   requeue_quarantined,
                                                   scan_wal)
    path = args.wal or _default_spill_path()
    if args.spill_command == "status":
        s = scan_wal(path)
        if not s["exists"]:
            _print(f"No spill WAL at {path} (nothing ever spilled).")
            return 0
        _print(f"Spill WAL {path}:")
        _print(f"  records total/pending: {s['totalRecords']} / "
               f"{s['pendingRecords']}")
        _print(f"  bytes valid/pending:   {s['validBytes']} / "
               f"{s['pendingBytes']}")
        if s["tornBytes"]:
            _print(f"  torn tail: {s['tornBytes']} byte(s) (repaired on "
                   "the owning server's next open)")
        _print(f"  drain cursor: {s['cursor']}")
        _print(f"  quarantined:  {s['quarantined']} record(s)"
               + (f" in {path}.quarantine" if s["quarantined"] else ""))
        return 0
    if args.spill_command == "peek":
        shown = 0
        if args.quarantine:
            for rec in read_quarantine(path)[:args.n]:
                _print("QUARANTINED " + _json.dumps(rec, sort_keys=True))
                shown += 1
        else:
            for rec in iter_pending(path, limit=args.n):
                _print(_json.dumps(rec, sort_keys=True))
                shown += 1
        if shown == 0:
            _print("No pending spill records."
                   if not args.quarantine else "Quarantine is empty.")
        return 0
    if args.spill_command == "requeue":
        q = read_quarantine(path)
        if not q:
            _print("Quarantine is empty; nothing to requeue.")
            return 0
        if not args.force and not _confirm(
                f"Retry {len(q)} quarantined record(s) against the "
                "primary event store?"):
            return 1
        done, kept = requeue_quarantined(path)
        _print(f"Requeued {done} record(s) directly into the event "
               "store (id-deduped)."
               + (f" {kept} still-rejected record(s) remain "
                  f"quarantined in {path}.quarantine." if kept
                  else " Quarantine cleared."))
        return 0 if not kept else 1
    _print("spill command must be status|peek|requeue")
    return 1


def cmd_lint(args) -> int:
    """Static concurrency + JAX hot-path analyzer (ISSUE 8): the
    whole-repo AST pass behind the tier-1 zero-new-findings gate.
    Heavy lifting lives in analysis/runner.py; this shim forwards the
    already-parsed flags so `pio lint --json` and the standalone runner
    agree exactly."""
    from predictionio_tpu.analysis.runner import main as lint_main
    argv = []
    if args.json:
        argv.append("--json")
    if args.root:
        argv.extend(["--root", args.root])
    if args.baseline:
        argv.extend(["--baseline", args.baseline])
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.update_baseline:
        argv.append("--update-baseline")
    return lint_main(argv)


def cmd_cache(args) -> int:
    """`pio cache {status,clear}` (ISSUE 9): the persistent XLA compile
    cache under base_dir()/xla_cache/<salt>. `status` reports the
    active salted directory, entry count/bytes, dead-salt dirs left by
    kernel changes, and the process's hit/miss counters; `clear`
    removes the active salt's entries (safe live — jax re-creates them
    on the next compile), `clear --all` also removes dead salts."""
    import json as _json
    from predictionio_tpu.compile.cache import (cache_status, clear_cache,
                                                enable_persistent_cache)
    if args.cache_cmd == "status":
        enable_persistent_cache()
        _print(_json.dumps(cache_status(), indent=2, default=str))
        return 0
    if args.cache_cmd == "clear":
        out = clear_cache(all_salts=args.all)
        _print(_json.dumps(out))
        return 0
    _print("cache command must be status|clear")
    return 1


def cmd_tenants(args) -> int:
    """`pio tenants {list,status,signals,evict,pin,unpin}`: the
    multi-tenant serving host's operator surface — which engines are
    packed on the device, what each one's factor tables cost in HBM,
    the evict/pin levers the packing runbook uses, and the per-tenant
    SLO/cost signals row (ISSUE 17)."""
    import json as _json

    import urllib.error
    import urllib.request

    from predictionio_tpu.utils.http import fetch_json
    base = args.url.rstrip("/")
    sub = args.tenants_command

    def _post(path):
        try:
            req = urllib.request.Request(base + path, data=b"",
                                         method="POST")
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, _json.loads(e.read())
            except Exception:
                return e.code, {"error": str(e)}
        except Exception as e:
            return None, {"error": str(e)}

    if sub in ("list", "status"):
        out = fetch_json(base + "/stats.json", timeout=10)
        if "error" in out:
            _print(f"serving host unreachable at {base}: "
                   f"{out['error']}")
            return 1
        tenants = out.get("tenants") or {}
        if getattr(args, "tenant", None):
            t = tenants.get(args.tenant)
            if t is None:
                _print(f"unknown tenant {args.tenant!r}; admitted: "
                       f"{sorted(tenants)}")
                return 1
            _print(_json.dumps(t, indent=2, default=str))
            return 0
        budget = out.get("budget") or {}
        bb = budget.get("budgetBytes")
        _print(f"Serving host at {base}: {len(tenants)} tenant(s), "
               f"{budget.get('residentBytes', 0)} HBM bytes resident"
               + (f" of {bb} budget" if bb else " (no budget)"))
        if sub == "list":
            for k in sorted(tenants):
                t = tenants[k]
                pin = " pinned" if t.get("pinned") else ""
                _print(f"  {k:20s} v={t.get('modelVersion') or '-':<18} "
                       f"hbm={t.get('hbmBytes', 0):>10} "
                       f"req={t.get('requests', 0):<8} "
                       f"evictions={t.get('evictions', 0)}{pin}")
            return 0
        _print(_json.dumps(tenants, indent=2, default=str))
        return 0
    if sub == "signals":
        out = fetch_json(base + "/tenants/signals.json", timeout=10)
        if "error" in out:
            _print(f"serving host unreachable at {base}: "
                   f"{out['error']}")
            return 1
        tenants = out.get("tenants") or {}
        if getattr(args, "tenant", None):
            t = tenants.get(args.tenant)
            if t is None:
                _print(f"unknown tenant {args.tenant!r}; admitted: "
                       f"{sorted(tenants)}")
                return 1
            _print(_json.dumps(t, indent=2, default=str))
            return 0
        _print(f"Serving host at {base}: {len(tenants)} tenant(s), "
               f"{out.get('residentBytes', 0)} HBM bytes resident")
        for k in sorted(tenants):
            t = tenants[k]
            p99 = t.get("serveP99Ms")
            _print(f"  {k:20s} {t.get('sloStatus', '?'):8s} "
                   f"rps={t.get('trafficEwmaRps', 0):<8} "
                   f"p99={'%.1fms' % p99 if p99 is not None else '-':<9} "
                   f"burn={t.get('burnFast')}/{t.get('burnSlow')} "
                   f"dev={t.get('deviceTimeShare', 0):<7} "
                   f"occ={t.get('occupancyShare', 0):<7} "
                   f"hbm={t.get('hbmBytes', 0):>10} "
                   f"stale={t.get('modelStalenessS', 0):.0f}s "
                   f"evictions={t.get('evictions', 0)}")
        return 0
    if sub in ("evict", "pin", "unpin"):
        st, out = _post(f"/tenants/{args.tenant}/{sub}")
        _print(_json.dumps(out, indent=2, default=str))
        return 0 if st == 200 else 1
    _print("tenants command must be list|status|evict|pin|unpin|signals")
    return 1


def cmd_placement(args) -> int:
    """`pio placement {status,plan,apply}` (ISSUE 18): the fleet
    tenant control plane's operator surface — where every tenant is
    placed (and under which generation), what the planner would do
    about budget pressure, and the lever that executes the planned
    migrations one observed step at a time."""
    import json as _json

    from predictionio_tpu.obs import fleet as F
    from predictionio_tpu.tenancy.controller import PlacementController
    reg = F.FleetRegistry(fleet_dir=getattr(args, "dir", None)) \
        if getattr(args, "dir", None) else F.get_fleet()
    ctl = PlacementController(registry=reg)
    sub = args.placement_command
    if sub == "status":
        st = ctl.status()
        if getattr(args, "json", False):
            _print(_json.dumps(st, indent=2, default=str))
            return 0
        hosts = st["hosts"]
        if not hosts:
            _print("no serving hosts registered (are they running "
                   "with this PIO_FS_BASEDIR?)")
            return 1
        for h in hosts:
            bb = h.get("budgetBytes")
            _print(f"{h['memberId']:28s} "
                   f"{'UP' if h['alive'] else 'DEAD':5s} "
                   f"{h.get('url') or '-':<26} "
                   f"hbm={h['usedBytes']}"
                   + (f"/{bb}" if bb else " (no budget)"))
            for k, t in h["tenants"].items():
                pin = " pinned" if t.get("pinned") else ""
                _print(f"    {k:20s} gen={t['generation']:<4} "
                       f"prio={t['priority']:<3} "
                       f"hbm={t['hbmBytes']:>10} "
                       f"rps={t['trafficEwmaRps']:<8} "
                       f"slo={t['sloStatus']}{pin}")
        slo = st.get("slo") or {}
        _print(f"controller SLO: {slo.get('status', 'no_data')}")
        dead_with_tenants = [h["memberId"] for h in hosts
                             if not h["alive"] and h["tenants"]]
        if dead_with_tenants:
            _print(f"DEAD hosts still holding tenants: "
                   f"{dead_with_tenants} (run a controller, or "
                   f"`pio placement apply` after it fails them over)")
            return 1
        return 0
    if sub == "plan":
        out = ctl.plan()
        decisions = out["rebalance"]["decisions"]
        if getattr(args, "json", False):
            _print(_json.dumps(out, indent=2, default=str))
            return 0
        if not decisions:
            _print("nothing to do: no live host is under budget "
                   "pressure")
            return 0
        for d in decisions:
            _print(f"  {d['action']:8s} {d['tenant']:20s} "
                   f"{d.get('fromHost', '-')} -> {d.get('host', '-')} "
                   f"({d.get('reason', '')})")
        return 0
    if sub == "apply":
        # one failover pass first (a dead host's stranded tenants are
        # more urgent than budget pressure), then the rebalance moves
        step = ctl.step()
        for a in step.get("actions", ()):
            _print(f"failover executed for {a['failover']}")
        moves = ctl.apply_rebalance()
        if not moves and not step.get("actions"):
            _print("nothing to do")
            return 0
        for m in moves:
            _print(f"migrated {m['tenant']}: {m['from']} -> {m['to']} "
                   f"(generation {m['generation']})")
        return 0
    _print("placement command must be status|plan|apply")
    return 1


def cmd_profile(args) -> int:
    """`pio profile top` (ISSUE 11): the running server's always-on
    sampling profiler, as a folded-stack top table — where the process
    spends its Python time RIGHT NOW, no restart, no instrumentation
    deploy. `pio profile trace {start,stop}` toggles the jax.profiler
    device trace on the same endpoint."""
    from predictionio_tpu.utils.http import fetch_json as _fetch_json
    base = f"http://{args.ip}:{args.port}"
    if args.profile_command == "top":
        out = _fetch_json(
            f"{base}/profile.json?action=report&top={args.n}")
        if "error" in out:
            _print(f"unreachable: {out['error']}")
            return 1
        _print(f"Sampling profiler at {base} "
               f"(hz={out.get('hz')}, samples={out.get('samples')}, "
               f"wall={out.get('wallS')}s, "
               f"overhead={out.get('overheadPct')}%)")
        stacks = out.get("topStacks") or []
        if not stacks:
            _print("  no samples yet (PIO_PROFILER=off, or the server "
                   "just started)")
            return 0
        for s in stacks:
            _print(f"  {s['pct']:6.2f}%  {s['count']:6d}  "
                   f"{s['stack']}")
        return 0
    if args.profile_command == "trace":
        import json as _json
        import urllib.request
        body = {"action": args.trace_action}
        if args.trace_action == "start" and args.dir:
            body["dir"] = args.dir
        req = urllib.request.Request(
            f"{base}/profile.json",
            data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                _print(_json.dumps(_json.loads(resp.read()), indent=2))
            return 0
        except Exception as e:
            _print(f"unreachable: {e}")
            return 1
    _print("profile command must be top|trace")
    return 1


def cmd_upgrade(args) -> int:
    """(Console upgrade / WorkflowUtils.checkUpgrade — the reference phones
    home for new versions; this build is offline, so upgrade is a no-op
    version report.)"""
    import predictionio_tpu
    _print(f"pio-tpu {predictionio_tpu.__version__}: offline build; "
           "no upgrade channel configured.")
    return 0


def _confirm(question: str) -> bool:
    answer = input(f"{question} (Y/n) ")
    return answer in ("", "y", "Y")


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio",
        description="pio-tpu: TPU-native machine-learning server")
    p.add_argument("--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(func=cmd_version)
    st = sub.add_parser("status")
    st.add_argument("--telemetry", action="store_true",
                    help="also poll the running servers' /stats.json + "
                         "/traces.json and print the compact operator "
                         "view (counters, latency percentiles, fold "
                         "activity, slowest traces)")
    st.add_argument("--ip", default="127.0.0.1")
    st.add_argument("--engine-port", type=int, default=8000)
    st.add_argument("--event-server-port", type=int, default=7070)
    st.add_argument("--accesskey", default="",
                    help="event-server access key for its /stats.json")
    st.add_argument("--slo", action="store_true",
                    help="also poll the running servers' /health.json "
                         "and print each SLO's status and fast/slow "
                         "burn rates (ISSUE 6)")
    st.add_argument("--url",
                    help="point --telemetry/--slo at ONE explicit "
                         "fleet member (http://host:port) instead of "
                         "the local engine+event defaults (ISSUE 13)")
    st.set_defaults(func=cmd_status)

    b = sub.add_parser("build")
    _add_variant_arg(b)
    b.set_defaults(func=cmd_build)

    un = sub.add_parser("unregister")
    _add_variant_arg(un)
    un.set_defaults(func=cmd_unregister)

    t = sub.add_parser("train")
    _add_variant_arg(t)
    t.add_argument("--engine-id")
    t.add_argument("--engine-version")
    t.add_argument("--engine-factory")
    t.add_argument("--engine-params-key",
                   help="train with the factory's named programmatic "
                        "params instead of the variant JSON "
                        "(EngineFactory.engine_params(key))")
    t.add_argument("--batch")
    t.add_argument("--skip-sanity-check", action="store_true")
    t.add_argument("--stop-after-read", action="store_true")
    t.add_argument("--stop-after-prepare", action="store_true")
    t.set_defaults(func=cmd_train)

    e = sub.add_parser("eval")
    e.add_argument("evaluation_class")
    e.add_argument("engine_params_generator_class", nargs="?")
    _add_variant_arg(e)
    e.add_argument("--batch")
    e.set_defaults(func=cmd_eval)

    d = sub.add_parser("deploy")
    d.add_argument("--ip", default="0.0.0.0")
    d.add_argument("--port", type=int, default=8000)
    _add_variant_arg(d)
    d.add_argument("--engine-id")
    d.add_argument("--engine-version")
    d.add_argument("--engine-instance-id")
    d.add_argument("--feedback", action="store_true")
    d.add_argument("--event-server-ip", default="0.0.0.0")
    d.add_argument("--event-server-port", type=int, default=7070)
    d.add_argument("--accesskey")
    d.add_argument("--mesh-broadcast-bytes", type=int, default=1 << 16,
                   help="multi-process mesh query broadcast buffer size")
    d.add_argument("--canary-fraction", type=float, default=0.0,
                   help="guarded deploys (ISSUE 5): serve hot-swapped "
                        "model versions to this traffic fraction first "
                        "and auto-rollback on watchdog breach "
                        "(0 = swap immediately)")
    d.add_argument("--canary-window", type=float, default=30.0,
                   help="watchdog decision window seconds")
    d.set_defaults(func=cmd_deploy)

    u = sub.add_parser("undeploy")
    u.add_argument("--ip", default="127.0.0.1")
    u.add_argument("--port", type=int, default=8000)
    u.set_defaults(func=cmd_undeploy)

    upd = sub.add_parser(
        "update", help="online model updates: tail the event store, fold "
        "fresh events into the deployed model, publish versions, and "
        "/reload the serving process (ISSUE 1 delta-training)")
    _add_variant_arg(upd)
    upd.add_argument("--engine-id")
    upd.add_argument("--engine-version")
    upd.add_argument("--app-name",
                     help="event app (default: the variant's datasource "
                          "app_name)")
    upd.add_argument("--engine-ip", default="127.0.0.1",
                     help="deployed engine server to POST /reload to")
    upd.add_argument("--engine-port", type=int, default=8000,
                     help="deployed engine server port (0 = publish "
                          "only, no reload)")
    upd.add_argument("--follow", action="store_true",
                     help="keep tailing until ^C (default: one forced "
                          "fold-in tick)")
    upd.add_argument("--interval", type=float, default=2.0,
                     help="--follow poll cadence seconds")
    upd.add_argument("--max-deltas", type=int, default=256,
                     help="fold in after this many fresh events")
    upd.add_argument("--max-staleness", type=float, default=30.0,
                     help="... or once the oldest delta is this old (s)")
    upd.add_argument("--drift-ratio", type=float, default=1.5,
                     help="fold loss / anchor loss bound that escalates "
                          "to a full retrain")
    upd.set_defaults(func=cmd_update)

    ev = sub.add_parser("eventserver")
    ev.add_argument("--ip", default="0.0.0.0")
    ev.add_argument("--port", type=int, default=7070)
    ev.add_argument("--stats", action="store_true")
    ev.add_argument("--max-batch", type=int, default=50,
                    help="/batch/events.json size cap (default 50, the "
                         "reference wire limit); the columnar write "
                         "route has its own much larger bound")
    ev.set_defaults(func=cmd_eventserver)

    db = sub.add_parser("dashboard")
    db.add_argument("--ip", default="127.0.0.1")
    db.add_argument("--port", type=int, default=9000)
    db.add_argument("--engine-url", default="http://127.0.0.1:8000",
                    help="engine server the /telemetry view polls")
    db.add_argument("--event-server-url",
                    default="http://127.0.0.1:7070",
                    help="event server the /telemetry view polls")
    db.set_defaults(func=cmd_dashboard)

    adm = sub.add_parser("adminserver")
    adm.add_argument("--ip", default="127.0.0.1")
    adm.add_argument("--port", type=int, default=7071)
    adm.set_defaults(func=cmd_adminserver)

    a = sub.add_parser("app")
    asub = a.add_subparsers(dest="app_command", required=True)
    an = asub.add_parser("new")
    an.add_argument("name")
    an.add_argument("--id", type=int)
    an.add_argument("--description")
    an.add_argument("--access-key")
    asub.add_parser("list")
    ash = asub.add_parser("show")
    ash.add_argument("name")
    ad = asub.add_parser("delete")
    ad.add_argument("name")
    ad.add_argument("-f", "--force", action="store_true")
    add_ = asub.add_parser("data-delete")
    add_.add_argument("name")
    add_.add_argument("--channel")
    add_.add_argument("--all", action="store_true")
    add_.add_argument("-f", "--force", action="store_true")
    acn = asub.add_parser("channel-new")
    acn.add_argument("name")
    acn.add_argument("channel")
    acd = asub.add_parser("channel-delete")
    acd.add_argument("name")
    acd.add_argument("channel")
    acd.add_argument("-f", "--force", action="store_true")
    a.set_defaults(func=cmd_app)

    k = sub.add_parser("accesskey")
    ksub = k.add_subparsers(dest="accesskey_command", required=True)
    kn = ksub.add_parser("new")
    kn.add_argument("app_name")
    kn.add_argument("--key")
    kn.add_argument("--event", action="append")
    kl = ksub.add_parser("list")
    kl.add_argument("app_name", nargs="?")
    kd = ksub.add_parser("delete")
    kd.add_argument("key")
    k.set_defaults(func=cmd_accesskey)

    tp = sub.add_parser("template")
    tsub = tp.add_subparsers(dest="template_command", required=True)
    tl = tsub.add_parser("list")
    tl.add_argument("--gallery", help="template index URI "
                    "(default: $PIO_TEMPLATE_GALLERY)")
    tg = tsub.add_parser("get")
    tg.add_argument("name")
    tg.add_argument("directory")
    tg.add_argument("--gallery", help="template index URI "
                    "(default: $PIO_TEMPLATE_GALLERY)")
    tp.set_defaults(func=cmd_template)

    ex = sub.add_parser("export")
    ex.add_argument("--appid", type=int, required=True)
    ex.add_argument("--output", required=True)
    ex.add_argument("--channelid", type=int)
    ex.add_argument("--format", choices=["json", "parquet"],
                    default="json",
                    help="json = one wire-format event per line; "
                         "parquet = columnar (the reference's default "
                         "format, EventsToFile.scala:35)")
    ex.set_defaults(func=cmd_export)

    im = sub.add_parser("import")
    im.add_argument("--appid", type=int, required=True)
    im.add_argument("--input", required=True)
    im.add_argument("--channelid", type=int)
    im.add_argument("--format",
                    choices=["events", "parquet", "movielens"],
                    default="events",
                    help="events = JSON-lines (pio export's output); "
                         "parquet = pio export --format parquet output; "
                         "movielens = a real ML-100K u.data / "
                         "ML-20M ratings.csv file, directory, or .zip")
    im.set_defaults(func=cmd_import)

    tr = sub.add_parser("trim")
    tr.add_argument("--src-appid", type=int, required=True)
    tr.add_argument("--dst-appid", type=int, required=True)
    tr.add_argument("--start", help="ISO8601; keep events at/after this")
    tr.add_argument("--until", help="ISO8601; keep events before this")
    tr.add_argument("--src-channelid", type=int)
    tr.add_argument("--dst-channelid", type=int)
    tr.set_defaults(func=cmd_trim)

    sv = sub.add_parser("servers",
                        help="probe the stack's service ports")
    sv.add_argument("--ip", default="127.0.0.1")
    sv.add_argument("--event-server-port", type=int, default=7070)
    sv.add_argument("--engine-port", type=int, default=8000)
    sv.add_argument("--dashboard-port", type=int, default=9000)
    sv.add_argument("--admin-port", type=int, default=7071)
    sv.set_defaults(func=cmd_servers)

    sn = sub.add_parser(
        "snapshot", help="ship/restore nativelog shard snapshots to a "
        "remote blob URI (the HBase snapshot/export role)")
    snsub = sn.add_subparsers(dest="snapshot_command", required=True)
    sc = snsub.add_parser("create")
    sc.add_argument("--appid", type=int, required=True)
    sc.add_argument("--uri", required=True,
                    help="remote blob root, e.g. file:///backups")
    sc.add_argument("--name", help="snapshot name (default: UTC stamp)")
    sc.add_argument("--channelid", type=int)
    sr = snsub.add_parser("restore")
    sr.add_argument("--uri", required=True)
    sr.add_argument("--name", required=True)
    sr.add_argument("--appid", type=int,
                    help="restore into a different app id")
    sr.add_argument("--channelid", type=int)
    sr.add_argument("--force", action="store_true",
                    help="replace an existing non-empty namespace")
    sl = snsub.add_parser("list")
    sl.add_argument("--uri", required=True)
    sn.set_defaults(func=cmd_snapshot)

    bs = sub.add_parser(
        "bootstrap", help="stand up a new tenant from a snapshot: "
        "restore, train through the streaming bulk data plane, catch "
        "up the fold tail, then admit (ISSUE 16)")
    bs.add_argument("tenant", help="tenant key for the new slot")
    bs.add_argument("--snapshot", required=True, help="snapshot name")
    bs.add_argument("--uri", required=True,
                    help="snapshot blob root, e.g. file:///backups")
    _add_variant_arg(bs)
    bs.add_argument("--engine-factory")
    bs.add_argument("--app-name",
                    help="app to restore + train into (default: the "
                         "variant's datasource app_name)")
    bs.add_argument("--force", action="store_true",
                    help="replace an existing non-empty namespace")
    bs.add_argument("--no-stream", action="store_true",
                    help="train through the monolithic batch read "
                         "instead of the streaming data plane")
    bs.add_argument("--serve", action="store_true",
                    help="start a ServingHost and admit the tenant "
                         "once caught up (default: report only)")
    bs.add_argument("--ip", default="0.0.0.0")
    bs.add_argument("--port", type=int, default=8100)
    bs.set_defaults(func=cmd_bootstrap)

    r = sub.add_parser("run")
    r.add_argument("main_py")
    r.add_argument("args", nargs="*")
    r.set_defaults(func=cmd_run)

    up = sub.add_parser("upgrade")
    up.set_defaults(func=cmd_upgrade)

    ln = sub.add_parser(
        "lint", help="static concurrency + JAX hot-path analyzer "
        "(ISSUE 8): lock-order cycles, locks held across blocking "
        "calls, unguarded background-thread mutation, implicit host "
        "syncs, jit recompile hazards, hot-path cost. Exit 0 = zero "
        "findings outside conf/lint_baseline.json")
    ln.add_argument("--json", action="store_true",
                    help="machine-readable report (CI mode)")
    ln.add_argument("--root", default=None,
                    help="directory to analyze (default: the "
                         "predictionio_tpu package)")
    ln.add_argument("--baseline", default=None,
                    help="baseline file (default: conf/lint_baseline"
                         ".json)")
    ln.add_argument("--no-baseline", action="store_true",
                    help="report every finding, suppressing nothing")
    ln.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current finding "
                         "set (new entries get TODO justifications "
                         "you must edit)")
    ln.set_defaults(func=cmd_lint)

    ca = sub.add_parser(
        "cache", help="persistent XLA compile cache (ISSUE 9): the "
        "salted executable store under base_dir()/xla_cache that makes "
        "warmup compiles a once-per-machine cost")
    casub = ca.add_subparsers(dest="cache_cmd", required=True)
    casub.add_parser("status")
    cacl = casub.add_parser("clear")
    cacl.add_argument("--all", action="store_true",
                      help="also remove dead-salt directories left by "
                           "kernel changes")
    ca.set_defaults(func=cmd_cache)

    tn = sub.add_parser(
        "tenants", help="multi-tenant serving host (ISSUE 15): list "
        "the engines packed on one device, read their per-tenant HBM "
        "cost, and evict/pin tenants under the budget manager")
    tnsub = tn.add_subparsers(dest="tenants_command", required=True)
    tnl = tnsub.add_parser("list")
    tns = tnsub.add_parser("status")
    tns.add_argument("tenant", nargs="?",
                     help="one tenant's full status (default: all)")
    tne = tnsub.add_parser("evict")
    tne.add_argument("tenant")
    tnp = tnsub.add_parser("pin")
    tnp.add_argument("tenant")
    tnu = tnsub.add_parser("unpin")
    tnu.add_argument("tenant")
    tng = tnsub.add_parser(
        "signals", help="per-tenant SLO/cost signals: traffic, serve "
        "p50/p99, burn rates, HBM bytes, device-time and occupancy "
        "shares, staleness, evictions (ISSUE 17)")
    tng.add_argument("tenant", nargs="?",
                     help="one tenant's signals row (default: all)")
    for tsp in (tnl, tns, tne, tnp, tnu, tng):
        tsp.add_argument("--url", default="http://localhost:8100",
                         help="serving host base URL")
    tn.set_defaults(func=cmd_tenants)

    rb = sub.add_parser(
        "rollback", help="guarded deploys (ISSUE 5): demote model "
        "versions newer than the last-known-good pin and /reload the "
        "serving process")
    _add_variant_arg(rb)
    rb.add_argument("--engine-id")
    rb.add_argument("--engine-version")
    rb.add_argument("--to", metavar="INSTANCE_ID",
                    help="explicit rollback target (default: the "
                         "last-good pin, else the previous COMPLETED "
                         "version)")
    rb.add_argument("--engine-ip", default="127.0.0.1")
    rb.add_argument("--engine-port", type=int, default=8000,
                    help="deployed engine server to POST /reload to "
                         "(0 = registry-only, no reload)")
    rb.set_defaults(func=cmd_rollback)

    spl = sub.add_parser(
        "spill", help="inspect the durable ingest-spill WAL and its "
        "quarantine sidecar (ISSUE 3 spill, ISSUE 5 tooling)")
    spsub = spl.add_subparsers(dest="spill_command", required=True)
    sps = spsub.add_parser("status")
    sps.add_argument("--wal", help="WAL path (default: "
                     "<PIO_FS_BASEDIR>/ingest_spill/events.wal)")
    spp = spsub.add_parser("peek")
    spp.add_argument("n", type=int, nargs="?", default=10,
                     help="records to show (default 10)")
    spp.add_argument("--wal")
    spp.add_argument("--quarantine", action="store_true",
                     help="peek the quarantine sidecar instead of the "
                          "pending WAL records")
    spr = spsub.add_parser("requeue")
    spr.add_argument("--wal")
    spr.add_argument("-f", "--force", action="store_true")
    spl.set_defaults(func=cmd_spill)

    inc = sub.add_parser(
        "incidents", help="browse the diagnostics plane's postmortem "
        "bundles (ISSUE 6): automatic captures from rollbacks, "
        "sentinel breaches, gate rejections and breaker opens")
    incsub = inc.add_subparsers(dest="incidents_command", required=True)
    inl = incsub.add_parser("list")
    inl.add_argument("--dir", help="incidents dir (default: "
                     "<PIO_FS_BASEDIR>/incidents)")
    inl.add_argument("--url", help="browse a fleet member's bundles "
                     "over HTTP (http://host:port) instead of the "
                     "local base_dir (ISSUE 13)")
    ins = incsub.add_parser("show")
    ins.add_argument("id")
    ins.add_argument("--dir")
    ins.add_argument("--url", help="load the bundle from a fleet "
                     "member over HTTP instead of the local base_dir")
    ine = incsub.add_parser("export")
    ine.add_argument("id")
    ine.add_argument("--out", help="output path (default ./<id>.tar.gz)")
    ine.add_argument("--dir")
    ine.add_argument("--url", help="rejected with a pointer (export "
                     "needs the member's filesystem)")
    inc.set_defaults(func=cmd_incidents)

    fl = sub.add_parser(
        "fleet", help="fleet observability (ISSUE 13): member registry "
        "liveness, the federated {role,pid}-labeled metrics scrape, "
        "and cross-process trace stitching")
    flsub = fl.add_subparsers(dest="fleet_command", required=True)
    fls = flsub.add_parser("status")
    fls.add_argument("--dir", help="fleet registry dir (default: "
                     "<PIO_FS_BASEDIR>/fleet)")
    flm = flsub.add_parser("metrics")
    flm.add_argument("--dir")
    flt = flsub.add_parser("traces")
    flt.add_argument("id", help="the trace id to stitch fleet-wide "
                     "(e.g. the traceId an event POST returned)")
    flt.add_argument("-n", type=int, default=50,
                     help="per-member neighborhood cap")
    flt.add_argument("--dir")
    fl.set_defaults(func=cmd_fleet)

    pf = sub.add_parser(
        "profile", help="runtime attribution (ISSUE 11): read the "
        "running server's always-on sampling profiler, or toggle a "
        "jax.profiler device trace")
    pfsub = pf.add_subparsers(dest="profile_command", required=True)
    pft = pfsub.add_parser("top")
    pft.add_argument("-n", type=int, default=20,
                     help="stacks to show (default 20)")
    pft.add_argument("--ip", default="127.0.0.1")
    pft.add_argument("--port", type=int, default=8000,
                     help="server to read (engine 8000; the event "
                          "server exposes the same endpoint behind "
                          "--stats)")
    pftr = pfsub.add_parser("trace")
    pftr.add_argument("trace_action", choices=("start", "stop"))
    pftr.add_argument("--dir", help="trace output dir (start only)")
    pftr.add_argument("--ip", default="127.0.0.1")
    pftr.add_argument("--port", type=int, default=8000)
    pf.set_defaults(func=cmd_profile)

    pl = sub.add_parser(
        "placement", help="fleet tenant control plane (ISSUE 18): "
        "per-host placements and generations, the rebalance plan, and "
        "one-shot failover + migration execution")
    plsub = pl.add_subparsers(dest="placement_command", required=True)
    pls = plsub.add_parser("status")
    pls.add_argument("--dir", help="fleet registry dir (default: "
                     "<PIO_FS_BASEDIR>/fleet)")
    pls.add_argument("--json", action="store_true",
                     help="full machine-readable status")
    plp = plsub.add_parser("plan")
    plp.add_argument("--dir")
    plp.add_argument("--json", action="store_true")
    pla = plsub.add_parser("apply")
    pla.add_argument("--dir")
    pl.set_defaults(func=cmd_placement)

    fl = sub.add_parser(
        "faults", help="chaos-harness control: validate a PIO_FAULTS "
        "spec and preview its seeded decisions")
    fl.add_argument("--spec", help="fault spec (default: $PIO_FAULTS)")
    fl.add_argument("--preview", type=int, default=0, metavar="N",
                    help="print the first N seeded decisions")
    fl.add_argument("--target", default="storage.write",
                    help="target for --preview (default storage.write)")
    fl.set_defaults(func=cmd_faults)

    return p


def _add_variant_arg(sp):
    """The engine-variant file flag shared by build/unregister/train/
    eval/deploy; --variant/-v are the reference's spellings
    (Console.scala:161)."""
    sp.add_argument("--engine-json", "--variant", "-v",
                    dest="engine_json", default="engine.json",
                    help="engine variant JSON (reference: --variant/-v)")


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="[%(levelname)s] [%(name)s] %(message)s")
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
