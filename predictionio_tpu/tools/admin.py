"""Admin REST API (experimental, parity with the reference's AdminAPI).

Rebuilds the reference's admin server
(reference: tools/src/main/scala/io/prediction/tools/admin/AdminAPI.scala:66-105
and CommandClient.scala:58+): app management over REST —
  GET    /                    -> status
  GET    /cmd/app             -> list apps
  POST   /cmd/app             -> create app {name, id?, description?}
  DELETE /cmd/app/<name>      -> delete app
  DELETE /cmd/app/<name>/data -> delete app data
"""

from __future__ import annotations

from dataclasses import dataclass

from predictionio_tpu.tools import app_commands as ac
from predictionio_tpu.utils.http import (HttpServer, Request, Response,
                                         Router)


@dataclass
class AdminServerConfig:
    ip: str = "127.0.0.1"
    port: int = 7071


class AdminServer:
    def __init__(self, config: AdminServerConfig = AdminServerConfig()):
        self.config = config
        self.router = self._build_router()
        self.server = None

    def _status(self, req: Request) -> Response:
        return Response(200, {"status": "alive"})

    def _list_apps(self, req: Request) -> Response:
        apps = [{"name": d.app.name, "id": d.app.id,
                 "description": d.app.description,
                 "accessKeys": [k.key for k in d.access_keys],
                 "channels": [c.name for c in d.channels]}
                for d in ac.app_list()]
        return Response(200, {"status": 1, "apps": apps})

    def _new_app(self, req: Request) -> Response:
        d = req.json() or {}
        if "name" not in d:
            return Response(400, {"message": "isEmpty appName"})
        try:
            desc = ac.app_new(d["name"], app_id=int(d.get("id") or 0),
                              description=d.get("description"))
            return Response(200, {
                "status": 1, "message": "App created successfully.",
                "id": desc.app.id, "name": desc.app.name,
                "key": desc.access_keys[0].key})
        except ac.AppCommandError as e:
            return Response(409, {"message": str(e)})

    def _delete_app(self, req: Request) -> Response:
        try:
            ac.app_delete(req.path_args[0])
            return Response(200, {
                "status": 1,
                "message": f"App {req.path_args[0]} was deleted."})
        except ac.AppCommandError as e:
            return Response(404, {"message": str(e)})

    def _delete_data(self, req: Request) -> Response:
        try:
            ac.app_data_delete(req.path_args[0])
            return Response(200, {
                "status": 1,
                "message": f"Data of app {req.path_args[0]} was deleted."})
        except ac.AppCommandError as e:
            return Response(404, {"message": str(e)})

    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self._status)
        r.add("GET", "/cmd/app", self._list_apps)
        r.add("POST", "/cmd/app", self._new_app)
        r.add("DELETE", "/cmd/app/<name>", self._delete_app)
        r.add("DELETE", "/cmd/app/<name>/data", self._delete_data)
        return r

    def start(self, background: bool = True) -> "AdminServer":
        srv = HttpServer(self.router, self.config.ip, self.config.port)
        self.server = srv
        srv.start(background=background)
        # read the port from the local: a concurrent stop() (signal
        # handler) may null self.server the instant serve_forever returns
        self.config.port = srv.port
        return self

    def stop(self):
        if self.server:
            self.server.stop()
            self.server = None
