"""Offline engine-template gallery.

Plays the role of the reference's GitHub-backed template tool
(reference: tools/src/main/scala/io/prediction/tools/console/Template.scala:130-416
`pio template list/get`) with the built-in template families shipped
in-tree: `get` scaffolds a working engine directory (engine.json + README +
seed script) wired to the corresponding predictionio_tpu.models factory.
"""

from __future__ import annotations

import json
import os

TEMPLATES = {
    "recommendation": {
        "description": "Explicit-ALS personalized recommendation "
                       "(rate/buy events)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "recommendation",
            "datasource": {"params": {"app_name": "MyApp"}},
            "preparator": {"params": {"dedup": "latest"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 10, "num_iterations": 20, "lam": 0.01, "seed": 3}}],
        },
        "query_example": {"user": "1", "num": 4},
    },
    "classification": {
        "description": "Naive-bayes classification over $set user "
                       "properties",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "classification",
            "datasource": {"params": {"app_name": "MyApp", "eval_k": 5}},
            "algorithms": [{"name": "naive", "params": {"lam": 1.0}}],
        },
        "query_example": {"attr0": 2, "attr1": 0, "attr2": 0},
    },
    "similarproduct": {
        "description": "Implicit-ALS similar-item recommendation "
                       "(view events)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "similarproduct",
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 10, "num_iterations": 20, "lam": 0.01,
                "alpha": 1.0, "seed": 3}}],
        },
        "query_example": {"items": ["i1"], "num": 4},
    },
    "recommendeduser": {
        "description": "Implicit-ALS similar-user recommendation "
                       "(follow events)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "recommendeduser",
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 10, "num_iterations": 20, "lam": 0.01, "seed": 3}}],
        },
        "query_example": {"users": ["u1"], "num": 4},
    },
    "ecommercerecommendation": {
        "description": "ALS + live business rules (seen-item/"
                       "unavailable-item blacklists)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "ecommercerecommendation",
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "ecomm", "params": {
                "app_name": "MyApp", "unseen_only": True,
                "seen_events": ["buy", "view"], "rank": 10,
                "num_iterations": 20, "lam": 0.01, "alpha": 1.0,
                "seed": 3}}],
        },
        "query_example": {"user": "u1", "num": 4},
    },
}


def list_templates():
    return [(name, t["description"]) for name, t in sorted(TEMPLATES.items())]


def get_template(name: str, directory: str) -> int:
    if name not in TEMPLATES:
        print(f"Unknown template {name!r}. Try `pio template list`.")
        return 1
    t = TEMPLATES[name]
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "engine.json"), "w") as f:
        json.dump(t["engine_json"], f, indent=2)
        f.write("\n")
    with open(os.path.join(directory, "README.md"), "w") as f:
        f.write(f"""# {name} engine

{t['description']}

## Usage

    pio app new MyApp                # note the access key
    # ... send events to the event server (pio eventserver) ...
    pio train --engine-json engine.json
    pio deploy --engine-json engine.json --port 8000

    curl -H 'Content-Type: application/json' \\
      -d '{json.dumps(t['query_example'])}' \\
      http://localhost:8000/queries.json
""")
    print(f"Engine template {name} created in {directory}.")
    return 0
