"""Engine-template gallery: built-ins + URI-addressed remote index.

Plays the role of the reference's GitHub-backed template tool
(reference: tools/src/main/scala/io/prediction/tools/console/Template.scala:130-416
`pio template list/get` — templates.json index + tarball download +
extract). Two sources:

  - the built-in template families shipped in-tree (`get` scaffolds a
    working engine directory wired to a predictionio_tpu.models factory);
  - a gallery at a URI (``PIO_TEMPLATE_GALLERY`` env or
    ``pio template --gallery``): ``<root>/index.json`` lists
    ``{"templates": [{"name", "description", "archive"}]}`` and each
    archive is a .tar.gz fetched through the same scheme-adapter
    registry the model store uses (``file://`` built-in; http/gs/s3
    adapters plug in via ``remotefs.register_scheme``) and extracted
    with path-traversal protection. The reference's remote-index
    mechanism is therefore complete; pointing it at a network gallery
    is configuration, not code.
"""

from __future__ import annotations

import io
import json
import os
import posixpath
import tarfile

TEMPLATES = {
    "recommendation": {
        "description": "Explicit-ALS personalized recommendation "
                       "(rate/buy events)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "recommendation",
            "datasource": {"params": {"app_name": "MyApp"}},
            "preparator": {"params": {"dedup": "latest"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 10, "num_iterations": 20, "lam": 0.01, "seed": 3}}],
        },
        "query_example": {"user": "1", "num": 4},
    },
    "classification": {
        "description": "Naive-bayes classification over $set user "
                       "properties",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "classification",
            "datasource": {"params": {"app_name": "MyApp", "eval_k": 5}},
            "algorithms": [{"name": "naive", "params": {"lam": 1.0}}],
        },
        "query_example": {"attr0": 2, "attr1": 0, "attr2": 0},
    },
    "similarproduct": {
        "description": "Implicit-ALS similar-item recommendation "
                       "(view events)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "similarproduct",
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 10, "num_iterations": 20, "lam": 0.01,
                "alpha": 1.0, "seed": 3}}],
        },
        "query_example": {"items": ["i1"], "num": 4},
    },
    "recommendeduser": {
        "description": "Implicit-ALS similar-user recommendation "
                       "(follow events)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "recommendeduser",
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 10, "num_iterations": 20, "lam": 0.01, "seed": 3}}],
        },
        "query_example": {"users": ["u1"], "num": 4},
    },
    "ecommercerecommendation": {
        "description": "ALS + live business rules (seen-item/"
                       "unavailable-item blacklists)",
        "engine_json": {
            "id": "default",
            "description": "Default settings",
            "engineFactory": "ecommercerecommendation",
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "ecomm", "params": {
                "app_name": "MyApp", "unseen_only": True,
                "seen_events": ["buy", "view"], "rank": 10,
                "num_iterations": 20, "lam": 0.01, "alpha": 1.0,
                "seed": 3}}],
        },
        "query_example": {"user": "u1", "num": 4},
    },
}


class GalleryError(RuntimeError):
    pass


def _gallery_uri(gallery=None):
    return gallery or os.environ.get("PIO_TEMPLATE_GALLERY") or None


def _gallery_index(uri: str):
    """[{name, description, archive}] from <uri>/index.json. Every field
    is remote content: parse failures and unsafe archive paths become
    GalleryError, never tracebacks."""
    from predictionio_tpu.data.storage.remotefs import adapter_for
    adapter, root = adapter_for(uri)
    p = posixpath.join(root, "index.json")
    if not adapter.exists(p):
        raise GalleryError(f"no index.json at gallery {uri}")
    try:
        idx = json.loads(adapter.read(p).decode("utf-8"))
    except ValueError as e:
        raise GalleryError(f"index.json at {uri} is not valid JSON: {e}")
    out = []
    for e in idx.get("templates", []):
        if not isinstance(e, dict) or not e.get("name") \
                or not e.get("archive"):
            raise GalleryError(f"gallery entry missing name/archive: {e}")
        arc = e["archive"]
        if (arc.startswith(("/", "\\")) or ".." in arc.split("/")
                or (len(arc) > 1 and arc[1] == ":")):
            # the index must not reach outside its own root
            raise GalleryError(f"unsafe archive path {arc!r} in index")
        out.append(e)
    return out


def _safe_extract(data: bytes, directory: str) -> int:
    """Extract a .tar.gz, refusing absolute paths, parent escapes, links,
    and devices (the index is remote content — never trust member
    names). ALL members are validated before anything is written, so a
    rejected archive leaves no partial, plausible-looking engine
    directory behind. Returns the number of files written."""
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tf:
            members = tf.getmembers()      # parses every header up front
            for m in members:
                name = m.name
                if (name.startswith(("/", "\\"))
                        or ".." in name.split("/")
                        or (len(name) > 1 and name[1] == ":")):
                    raise GalleryError(f"unsafe archive member {name!r}")
                if not (m.isdir() or m.isreg()):
                    raise GalleryError(
                        f"archive member {name!r} is not a regular file "
                        f"(links/devices are refused)")
            n = 0
            for m in members:
                if m.isdir():
                    os.makedirs(os.path.join(directory, m.name),
                                exist_ok=True)
                    continue
                dst = os.path.join(directory, m.name)
                os.makedirs(os.path.dirname(dst) or directory,
                            exist_ok=True)
                src = tf.extractfile(m)
                with open(dst, "wb") as f:
                    f.write(src.read())
                n += 1
            return n
    except tarfile.TarError as e:
        raise GalleryError(f"archive is not a valid tar.gz: {e}")


def list_templates(gallery=None):
    """Built-ins plus, when a gallery URI is configured, its index
    entries (gallery wins on name collisions, as the reference's remote
    index shadows nothing local — there was nothing local there)."""
    out = {name: t["description"] for name, t in TEMPLATES.items()}
    uri = _gallery_uri(gallery)
    if uri:
        for e in _gallery_index(uri):
            out[e["name"]] = ((e.get("description") or "")
                              + f" [gallery {uri}]")
    return sorted(out.items())


def get_template(name: str, directory: str, gallery=None) -> int:
    uri = _gallery_uri(gallery)
    if uri:
        entries = {e["name"]: e for e in _gallery_index(uri)}
        if name in entries:
            from predictionio_tpu.data.storage.remotefs import adapter_for
            adapter, root = adapter_for(uri)
            blob = posixpath.join(root, entries[name]["archive"])
            if not adapter.exists(blob):
                raise GalleryError(
                    f"gallery index names {entries[name]['archive']!r} "
                    f"but the blob is missing at {uri}")
            os.makedirs(directory, exist_ok=True)
            n = _safe_extract(adapter.read(blob), directory)
            print(f"Engine template {name} created in {directory} "
                  f"({n} file(s) from {uri}).")
            return 0
    if name not in TEMPLATES:
        print(f"Unknown template {name!r}. Try `pio template list`.")
        return 1
    t = TEMPLATES[name]
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "engine.json"), "w") as f:
        json.dump(t["engine_json"], f, indent=2)
        f.write("\n")
    with open(os.path.join(directory, "README.md"), "w") as f:
        f.write(f"""# {name} engine

{t['description']}

## Usage

    pio app new MyApp                # note the access key
    # ... send events to the event server (pio eventserver) ...
    pio train --engine-json engine.json
    pio deploy --engine-json engine.json --port 8000

    curl -H 'Content-Type: application/json' \\
      -d '{json.dumps(t['query_example'])}' \\
      http://localhost:8000/queries.json
""")
    print(f"Engine template {name} created in {directory}.")
    return 0
