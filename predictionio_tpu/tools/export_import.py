"""Event export/import: event store <-> JSON-lines or parquet files.

Rebuilds the reference's export/import tools
(reference: tools/src/main/scala/io/prediction/tools/export/EventsToFile.scala:95
and imprt/FileToEvents.scala:39): one JSON event per line — the same
wire format as /events.json — or columnar parquet (the reference's
DEFAULT --format, EventsToFile.scala:35; here json stays the default
because it is the wire format, parquet is one flag away).
"""

from __future__ import annotations

import json as _json
from typing import Optional

from predictionio_tpu.data.event import Event, EventValidation
from predictionio_tpu.data.storage.registry import Storage


def export_events(app_id: int, output: str,
                  channel_id: Optional[int] = None) -> int:
    events = Storage.get_events()
    n = 0
    with open(output, "w") as f:
        for e in events.find(app_id=app_id, channel_id=channel_id):
            f.write(e.to_json())
            f.write("\n")
            n += 1
    return n


_PARQUET_COLS = ("eventId", "event", "entityType", "entityId",
                 "targetEntityType", "targetEntityId", "properties",
                 "eventTime", "tags", "prId", "creationTime")


def _parquet_schema():
    import pyarrow as pa
    return pa.schema([
        ("eventId", pa.string()), ("event", pa.string()),
        ("entityType", pa.string()), ("entityId", pa.string()),
        ("targetEntityType", pa.string()),
        ("targetEntityId", pa.string()),
        ("properties", pa.string()),
        ("eventTime", pa.timestamp("ms", tz="UTC")),
        ("tags", pa.list_(pa.string())), ("prId", pa.string()),
        ("creationTime", pa.timestamp("ms", tz="UTC")),
    ])


def export_events_parquet(app_id: int, output: str,
                          channel_id: Optional[int] = None,
                          batch_size: int = 10000) -> int:
    """Columnar export for analytics pipelines (the role of the
    reference's default parquet format, EventsToFile.scala:35,94).
    Schema mirrors the event wire format; free-form `properties` ride
    as a JSON string column (parquet wants a stable schema, and event
    properties deliberately have none — the reference's SQLContext
    json-infers per export, which bakes one batch's shape into the
    file; a JSON column round-trips losslessly instead). Streams in
    `batch_size` record batches — RAM stays O(batch), not O(events)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    schema = _parquet_schema()
    events = Storage.get_events()
    n = 0
    with pq.ParquetWriter(output, schema) as writer:
        cols = {c: [] for c in _PARQUET_COLS}

        def flush():
            nonlocal cols
            if cols["event"]:
                writer.write_batch(pa.record_batch(
                    [cols[c] for c in _PARQUET_COLS], schema=schema))
                cols = {c: [] for c in _PARQUET_COLS}

        for e in events.find(app_id=app_id, channel_id=channel_id):
            cols["eventId"].append(e.event_id)
            cols["event"].append(e.event)
            cols["entityType"].append(e.entity_type)
            cols["entityId"].append(e.entity_id)
            cols["targetEntityType"].append(e.target_entity_type)
            cols["targetEntityId"].append(e.target_entity_id)
            cols["properties"].append(
                _json.dumps(e.properties.fields, sort_keys=True))
            cols["eventTime"].append(e.event_time)
            cols["tags"].append(list(e.tags))
            cols["prId"].append(e.pr_id)
            cols["creationTime"].append(e.creation_time)
            n += 1
            if n % batch_size == 0:
                flush()
        flush()
    return n


def parquet_events(input_path: str, validate: bool = True):
    """Yield Events from a parquet file written by
    `export_events_parquet` (or any file matching its schema), one
    record batch at a time. Rows get the SAME scrutiny the JSON import
    path applies — required fields present, EventValidation rules —
    because foreign files are explicitly invited."""
    import pyarrow.parquet as pq

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import utcnow

    pf = pq.ParquetFile(input_path)
    for batch in pf.iter_batches():
        for row in batch.to_pylist():
            for req in ("event", "entityType", "entityId"):
                if not row.get(req):
                    raise ValueError(
                        f"parquet event missing required field "
                        f"{req!r}: {row!r}")
            props = _json.loads(row.get("properties") or "{}")
            if not isinstance(props, dict):
                raise ValueError(
                    "parquet event field 'properties' must be a JSON "
                    f"object, got {row.get('properties')!r}")
            e = Event(
                event=row["event"], entity_type=row["entityType"],
                entity_id=row["entityId"],
                target_entity_type=row.get("targetEntityType"),
                target_entity_id=row.get("targetEntityId"),
                properties=DataMap(props),
                event_time=row.get("eventTime") or utcnow(),
                tags=row.get("tags") or (),
                pr_id=row.get("prId"),
                creation_time=row.get("creationTime") or utcnow(),
                event_id=row.get("eventId"))
            if validate:
                EventValidation.validate(e)
            yield e


def _insert_batched(event_iter, app_id: int,
                    channel_id: Optional[int], batch_size: int) -> int:
    """Chunked insert_batch over an event iterator; returns the count."""
    events = Storage.get_events()
    batch = []
    n = 0
    for e in event_iter:
        batch.append(e)
        if len(batch) >= batch_size:
            events.insert_batch(batch, app_id, channel_id)
            n += len(batch)
            batch = []
    if batch:
        events.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    return n


def _movielens_lines(path: str):
    """Resolve `path` to (iterator of text lines, format) for a real
    MovieLens dataset. Accepts the ML-100K `u.data` TSV, the
    ML-20M/ml-latest `ratings.csv`, a directory containing either, or
    the published .zip archive of either — local files only, no network
    assumption. Format is "tsv" (user\\titem\\trating\\tts) or "csv"
    (userId,movieId,rating,timestamp header)."""
    import io
    import os
    import zipfile

    def fmt_of(name: str) -> str:
        return "tsv" if os.path.basename(name) == "u.data" else "csv"

    if path.endswith(".zip"):
        zf = zipfile.ZipFile(path)
        try:
            members = [n for n in zf.namelist()
                       if os.path.basename(n) in ("ratings.csv", "u.data")]
            if not members:
                raise ValueError(
                    f"{path}: no ratings.csv or u.data in the archive")
            member = members[0]
            wrapper = io.TextIOWrapper(zf.open(member), "utf-8")
        except Exception:
            zf.close()
            raise
        # the archive handle must live as long as the member stream and
        # close WITH it (not at GC's leisure)
        orig_close = wrapper.close

        def close_both():
            orig_close()
            zf.close()
        wrapper.close = close_both
        return wrapper, fmt_of(member)
    if os.path.isdir(path):
        for name in ("ratings.csv", "u.data"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                return open(cand, encoding="utf-8"), fmt_of(cand)
        raise ValueError(f"{path}: no ratings.csv or u.data in directory")
    return open(path, encoding="utf-8"), fmt_of(path)


def movielens_events(path: str):
    """Yield `rate` events from a real MovieLens dataset, in the exact
    shape the recommendation template's quickstart ingests — so the day
    real data is on disk, `pio import --format movielens` + `pio train`
    produce RMSE curves comparable to published ALS results with no new
    code. (reference DataSource contract the events feed:
    examples/scala-parallel-recommendation/custom-prepartor/src/main/
    scala/DataSource.scala:20-46)"""
    import datetime as dt

    from predictionio_tpu.data.datamap import DataMap

    f, fmt = _movielens_lines(path)
    with f:
        if fmt == "csv":
            header = f.readline().strip().lower()
            if not header.startswith("userid,movieid,rating"):
                raise ValueError(
                    f"{path}: expected a userId,movieId,rating,timestamp "
                    f"header, got {header[:60]!r}")
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split("\t" if fmt == "tsv" else ",")
            uid, mid, rating, ts = parts[0], parts[1], float(parts[2]), \
                int(parts[3])
            yield Event(
                event="rate", entity_type="user", entity_id=uid,
                target_entity_type="item", target_entity_id=mid,
                properties=DataMap({"rating": rating}),
                event_time=dt.datetime.fromtimestamp(
                    ts, tz=dt.timezone.utc))


def import_movielens(app_id: int, input_path: str,
                     channel_id: Optional[int] = None,
                     batch_size: int = 10000) -> int:
    return _insert_batched(movielens_events(input_path), app_id,
                           channel_id, batch_size)


def import_events_parquet(app_id: int, input_path: str,
                          channel_id: Optional[int] = None,
                          batch_size: int = 10000) -> int:
    return _insert_batched(parquet_events(input_path), app_id,
                           channel_id, batch_size)


def import_events(app_id: int, input_path: str,
                  channel_id: Optional[int] = None,
                  batch_size: int = 10000, validate: bool = True) -> int:
    def parsed():
        with open(input_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = Event.from_json(line)
                if validate:
                    EventValidation.validate(e)
                yield e

    return _insert_batched(parsed(), app_id, channel_id, batch_size)


def trim_events(src_app_id: int, dst_app_id: int,
                start_time=None, until_time=None,
                src_channel_id: Optional[int] = None,
                dst_channel_id: Optional[int] = None,
                batch_size: int = 10000) -> int:
    """Copy the [start_time, until_time) window of a source app's events
    into an EMPTY destination app — the trim workflow (keep only a recent
    window under a fresh app id). Both apps must be registered; the
    destination must be empty in EVERY channel, as the reference requires
    (reference: examples/experimental/scala-parallel-trim-app/src/main/
    scala/DataSource.scala:44-47)."""
    apps = Storage.get_meta_data_apps()
    for label, aid in (("source", src_app_id), ("destination", dst_app_id)):
        if apps.get(aid) is None:
            raise ValueError(f"{label} app {aid} does not exist; create "
                             f"it with `pio app new` first")
    events = Storage.get_events()
    dst_channels = [None] + [
        c.id for c in Storage.get_meta_data_channels()
        .get_by_app_id(dst_app_id)]
    for ch in dst_channels:
        if next(iter(events.find(app_id=dst_app_id, channel_id=ch,
                                 limit=1)), None):
            where = "default channel" if ch is None else f"channel {ch}"
            raise ValueError(
                f"destination app {dst_app_id} is not empty ({where}); "
                f"trim writes only into a fresh app")
    events.init(dst_app_id, dst_channel_id)
    return _insert_batched(
        events.find(app_id=src_app_id, channel_id=src_channel_id,
                    start_time=start_time, until_time=until_time),
        dst_app_id, dst_channel_id, batch_size)
