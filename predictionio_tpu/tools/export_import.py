"""Event export/import: event store <-> JSON-lines files.

Rebuilds the reference's export/import tools
(reference: tools/src/main/scala/io/prediction/tools/export/EventsToFile.scala:95
and imprt/FileToEvents.scala:39): one JSON event per line, the same wire
format as /events.json.
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.data.event import Event, EventValidation
from predictionio_tpu.data.storage.registry import Storage


def export_events(app_id: int, output: str,
                  channel_id: Optional[int] = None) -> int:
    events = Storage.get_events()
    n = 0
    with open(output, "w") as f:
        for e in events.find(app_id=app_id, channel_id=channel_id):
            f.write(e.to_json())
            f.write("\n")
            n += 1
    return n


def _insert_batched(event_iter, app_id: int,
                    channel_id: Optional[int], batch_size: int) -> int:
    """Chunked insert_batch over an event iterator; returns the count."""
    events = Storage.get_events()
    batch = []
    n = 0
    for e in event_iter:
        batch.append(e)
        if len(batch) >= batch_size:
            events.insert_batch(batch, app_id, channel_id)
            n += len(batch)
            batch = []
    if batch:
        events.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    return n


def import_events(app_id: int, input_path: str,
                  channel_id: Optional[int] = None,
                  batch_size: int = 10000, validate: bool = True) -> int:
    def parsed():
        with open(input_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = Event.from_json(line)
                if validate:
                    EventValidation.validate(e)
                yield e

    return _insert_batched(parsed(), app_id, channel_id, batch_size)


def trim_events(src_app_id: int, dst_app_id: int,
                start_time=None, until_time=None,
                src_channel_id: Optional[int] = None,
                dst_channel_id: Optional[int] = None,
                batch_size: int = 10000) -> int:
    """Copy the [start_time, until_time) window of a source app's events
    into an EMPTY destination app — the trim workflow (keep only a recent
    window under a fresh app id). Both apps must be registered; the
    destination must be empty in EVERY channel, as the reference requires
    (reference: examples/experimental/scala-parallel-trim-app/src/main/
    scala/DataSource.scala:44-47)."""
    apps = Storage.get_meta_data_apps()
    for label, aid in (("source", src_app_id), ("destination", dst_app_id)):
        if apps.get(aid) is None:
            raise ValueError(f"{label} app {aid} does not exist; create "
                             f"it with `pio app new` first")
    events = Storage.get_events()
    dst_channels = [None] + [
        c.id for c in Storage.get_meta_data_channels()
        .get_by_app_id(dst_app_id)]
    for ch in dst_channels:
        if next(iter(events.find(app_id=dst_app_id, channel_id=ch,
                                 limit=1)), None):
            where = "default channel" if ch is None else f"channel {ch}"
            raise ValueError(
                f"destination app {dst_app_id} is not empty ({where}); "
                f"trim writes only into a fresh app")
    events.init(dst_app_id, dst_channel_id)
    return _insert_batched(
        events.find(app_id=src_app_id, channel_id=src_channel_id,
                    start_time=start_time, until_time=until_time),
        dst_app_id, dst_channel_id, batch_size)
