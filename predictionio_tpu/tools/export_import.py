"""Event export/import: event store <-> JSON-lines files.

Rebuilds the reference's export/import tools
(reference: tools/src/main/scala/io/prediction/tools/export/EventsToFile.scala:95
and imprt/FileToEvents.scala:39): one JSON event per line, the same wire
format as /events.json.
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.data.event import Event, EventValidation
from predictionio_tpu.data.storage.registry import Storage


def export_events(app_id: int, output: str,
                  channel_id: Optional[int] = None) -> int:
    events = Storage.get_events()
    n = 0
    with open(output, "w") as f:
        for e in events.find(app_id=app_id, channel_id=channel_id):
            f.write(e.to_json())
            f.write("\n")
            n += 1
    return n


def import_events(app_id: int, input_path: str,
                  channel_id: Optional[int] = None,
                  batch_size: int = 10000, validate: bool = True) -> int:
    events = Storage.get_events()
    batch = []
    n = 0
    with open(input_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            e = Event.from_json(line)
            if validate:
                EventValidation.validate(e)
            batch.append(e)
            if len(batch) >= batch_size:
                events.insert_batch(batch, app_id, channel_id)
                n += len(batch)
                batch = []
    if batch:
        events.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    return n
