"""Tools & ops (L6): the `pio` CLI, app/accesskey management, export/import,
dashboard, admin API."""
