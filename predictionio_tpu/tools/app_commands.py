"""App / access-key / channel management commands.

Rebuilds the reference's console App commands
(reference: tools/src/main/scala/io/prediction/tools/console/App.scala —
create: insert App -> LEvents.init(appId) -> create AccessKey; list/show/
delete/data-delete; channel-new/channel-delete).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional

from predictionio_tpu.data.storage.base import AccessKey, App, Channel
from predictionio_tpu.data.storage.registry import Storage

logger = logging.getLogger(__name__)


class AppCommandError(Exception):
    pass


@dataclass
class AppDescription:
    app: App
    access_keys: List[AccessKey]
    channels: List[Channel]


def app_new(name: str, app_id: int = 0, description: Optional[str] = None,
            access_key: str = "") -> AppDescription:
    apps = Storage.get_meta_data_apps()
    if apps.get_by_name(name) is not None:
        raise AppCommandError(f"App {name} already exists. Aborting.")
    if app_id != 0 and apps.get(app_id) is not None:
        raise AppCommandError(f"App ID {app_id} already exists. Aborting.")
    new_id = apps.insert(App(app_id, name, description))
    if new_id is None:
        raise AppCommandError(f"Unable to create new app.")
    Storage.get_events().init(new_id)
    key = Storage.get_meta_data_access_keys().insert(
        AccessKey(access_key, new_id, []))
    if key is None:
        raise AppCommandError("Unable to create new access key.")
    app = apps.get(new_id)
    logger.info("Created app %s (id %d) with access key %s",
                name, new_id, key)
    return AppDescription(app=app,
                          access_keys=[AccessKey(key, new_id, [])],
                          channels=[])


def app_list() -> List[AppDescription]:
    apps = Storage.get_meta_data_apps().get_all()
    keys = Storage.get_meta_data_access_keys()
    channels = Storage.get_meta_data_channels()
    return [AppDescription(app=a, access_keys=keys.get_by_app_id(a.id),
                           channels=channels.get_by_app_id(a.id))
            for a in apps]


def app_show(name: str) -> AppDescription:
    app = Storage.get_meta_data_apps().get_by_name(name)
    if app is None:
        raise AppCommandError(f"App {name} does not exist. Aborting.")
    return AppDescription(
        app=app,
        access_keys=Storage.get_meta_data_access_keys().get_by_app_id(app.id),
        channels=Storage.get_meta_data_channels().get_by_app_id(app.id))


def app_delete(name: str) -> None:
    desc = app_show(name)
    events = Storage.get_events()
    for channel in desc.channels:
        events.remove(desc.app.id, channel.id)
        Storage.get_meta_data_channels().delete(channel.id)
    events.remove(desc.app.id)
    for k in desc.access_keys:
        Storage.get_meta_data_access_keys().delete(k.key)
    if not Storage.get_meta_data_apps().delete(desc.app.id):
        raise AppCommandError(f"Unable to delete app {name}.")
    logger.info("Deleted app %s.", name)


def app_data_delete(name: str, channel: Optional[str] = None,
                    delete_all: bool = False) -> None:
    desc = app_show(name)
    events = Storage.get_events()
    if delete_all:
        events.remove(desc.app.id)
        events.init(desc.app.id)
        for ch in desc.channels:
            events.remove(desc.app.id, ch.id)
            events.init(desc.app.id, ch.id)
        return
    if channel is not None:
        match = [c for c in desc.channels if c.name == channel]
        if not match:
            raise AppCommandError(
                f"Unable to delete data for channel. Channel {channel} "
                "doesn't exist.")
        events.remove(desc.app.id, match[0].id)
        events.init(desc.app.id, match[0].id)
    else:
        events.remove(desc.app.id)
        events.init(desc.app.id)


def channel_new(app_name: str, channel_name: str) -> Channel:
    desc = app_show(app_name)
    if any(c.name == channel_name for c in desc.channels):
        raise AppCommandError(
            f"Unable to create new channel. Channel {channel_name} already "
            "exists.")
    if not Channel.is_valid_name(channel_name):
        raise AppCommandError(
            f"Unable to create new channel. The channel name "
            f"{channel_name} is invalid. {Channel.NAME_CONSTRAINT}")
    cid = Storage.get_meta_data_channels().insert(
        Channel(0, channel_name, desc.app.id))
    if cid is None:
        raise AppCommandError("Unable to create new channel.")
    Storage.get_events().init(desc.app.id, cid)
    return Channel(cid, channel_name, desc.app.id)


def channel_delete(app_name: str, channel_name: str) -> None:
    desc = app_show(app_name)
    match = [c for c in desc.channels if c.name == channel_name]
    if not match:
        raise AppCommandError(
            f"Unable to delete channel. Channel {channel_name} doesn't "
            "exist.")
    Storage.get_events().remove(desc.app.id, match[0].id)
    if not Storage.get_meta_data_channels().delete(match[0].id):
        raise AppCommandError("Unable to delete channel.")


def accesskey_new(app_name: str, key: str = "",
                  events: Optional[List[str]] = None) -> AccessKey:
    desc = app_show(app_name)
    created = Storage.get_meta_data_access_keys().insert(
        AccessKey(key, desc.app.id, tuple(events or ())))
    if created is None:
        raise AppCommandError("Unable to create new access key.")
    return AccessKey(created, desc.app.id, tuple(events or ()))


def accesskey_list(app_name: Optional[str] = None) -> List[AccessKey]:
    dao = Storage.get_meta_data_access_keys()
    if app_name is None:
        return dao.get_all()
    return dao.get_by_app_id(app_show(app_name).app.id)


def accesskey_delete(key: str) -> None:
    if not Storage.get_meta_data_access_keys().delete(key):
        raise AppCommandError(f"Unable to delete access key {key}.")
