"""HBM budget manager: per-tenant residency accounting + eviction.

ALX (arXiv:2112.02194) frames TPU factorization throughput as a
function of what you keep resident in HBM; a multi-tenant host makes
that a *policy* question — which tenants' factor tables deserve the
device right now. This module owns the answer:

- **Accounting**: every upload and residency slot a tenant's
  query/fold paths create is tagged in ``utils/device_cache`` (the
  ``tenant_scope`` contextvar the slot servers and schedulers enter);
  :meth:`HBMBudgetManager.sizes` reads the live per-device bytes per
  tenant from the device arrays themselves — plus each slot's
  :class:`~predictionio_tpu.parallel.sharded_table.ShardedTable`
  resident handles via a host-provided sizer. The
  ``pio_engine_hbm_bytes{tenant}`` gauge samples exactly this.
- **Admission control**: a tenant whose PADDED tables (the
  compile-plane vocab buckets the serve path actually uploads at)
  exceed the whole budget can never fit — :meth:`admit` refuses it
  with :class:`TableBudgetExceeded` before it serves a single query,
  naming the sharded exit the error already documents.
- **Eviction**: when the budget is tight, :meth:`ensure_room` evicts
  the coldest unpinned tenants (priority first, then LRU by last hit)
  back to their host mirrors. Eviction drops device references only —
  the numpy/host-shard mirrors stay the source of truth, and the next
  hit re-uploads through the budget-checked ``cached_put_rows`` /
  ``ShardedTable.device`` cold paths. The host wires a per-slot
  evictor that quiesces in-flight windows first (PR 13 snapshot
  semantics extended to residency handles).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from predictionio_tpu.utils import device_cache
from predictionio_tpu.utils.device_cache import TableBudgetExceeded

logger = logging.getLogger(__name__)


def _iter_tables(models: Sequence[Any]):
    """Yield every distinct 2-D factor-table-shaped array (numpy or
    ShardedTable) reachable one attribute level deep from the models —
    the serve/fold paths keep exactly these resident."""
    from predictionio_tpu.parallel.sharded_table import is_sharded
    seen = set()
    frontier = []
    for m in models:
        frontier.append(m)
        als = getattr(m, "als", None)
        if als is not None:
            frontier.append(als)
    for obj in frontier:
        try:
            attrs = vars(obj)
        except TypeError:
            continue
        for v in attrs.values():
            if id(v) in seen:
                continue
            if is_sharded(v) or (isinstance(v, np.ndarray)
                                 and v.ndim == 2):
                seen.add(id(v))
                yield v


def estimate_padded_bytes(models: Sequence[Any]) -> int:
    """Per-device bytes the models' tables would pin once fully
    resident at their compile-plane vocab buckets — the admission
    estimate. Replicated tables cost their full padded bytes on every
    device; a sharded table costs its padded bytes / n_shards."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.parallel.sharded_table import is_sharded
    total = 0
    for t in _iter_tables(models):
        n, width = t.shape
        itemsize = np.dtype(t.dtype).itemsize
        if is_sharded(t):
            padded = B.bucket_rows_sharded(n, t.n_shards)
            total += (padded // t.n_shards) * width * itemsize
        else:
            total += B.bucket_rows(n) * width * itemsize
    return int(total)


class _TenantState:
    __slots__ = ("tenant", "expected_bytes", "priority", "pinned",
                 "last_hit", "admitted_at", "evictions", "sizer",
                 "evictor")

    def __init__(self, tenant: str, expected_bytes: int,
                 priority: int = 0, pinned: bool = False,
                 sizer: Optional[Callable[[], int]] = None,
                 evictor: Optional[Callable[[], None]] = None):
        self.tenant = tenant
        self.expected_bytes = int(expected_bytes)
        self.priority = int(priority)
        self.pinned = bool(pinned)
        self.last_hit = time.monotonic()
        self.admitted_at = time.time()
        self.evictions = 0
        # host-provided extras: sizer() returns the DEVICE ARRAYS this
        # tenant holds that device_cache cannot see (ShardedTable._dev
        # handles live on the table object) — arrays, not bytes, so
        # sizes() can identity-dedup them against the residency
        # payloads that carry the same handles; evictor() is the full
        # quiesce-then-drop mechanism
        self.sizer = sizer
        self.evictor = evictor

    def snapshot(self) -> dict:
        return {
            "expectedPaddedBytes": self.expected_bytes,
            "priority": self.priority,
            "pinned": self.pinned,
            "idleSec": round(time.monotonic() - self.last_hit, 3),
            "admittedAt": self.admitted_at,
            "evictions": self.evictions,
        }


class HBMBudgetManager:
    """Thread-safe per-tenant HBM accounting + eviction policy for one
    serving host. ``budget_bytes`` defaults to the enforced
    ``PIO_TABLE_BUDGET_BYTES`` (None = accounting only, no budget
    pressure — eviction still works by operator request)."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 registry=None):
        self.budget_bytes = (int(budget_bytes) if budget_bytes
                             else device_cache.table_budget_bytes())
        self._lock = threading.RLock()
        self._tenants: Dict[str, _TenantState] = {}
        self._c_evictions = None
        if registry is not None:
            registry.gauge_func(
                "pio_engine_hbm_bytes",
                "Per-device HBM bytes resident per serving tenant "
                "(factor tables + fold residency payloads), measured "
                "from the live device arrays",
                self._hbm_samples)
            registry.gauge_func(
                "pio_tenant_hbm_budget_bytes",
                "Enforced per-device HBM table budget for the host "
                "(0 = unenforced)",
                lambda: float(self.budget_bytes or 0))
            self._c_evictions = registry.counter(
                "pio_tenant_evictions_total",
                "Tenant factor-table evictions back to host mirrors, "
                "by tenant and reason (budget = room made for another "
                "tenant, operator = pio tenants evict / HTTP, "
                "remove = tenant removal)",
                labelnames=("tenant", "reason"))

    def _hbm_samples(self):
        sizes = self.sizes()
        with self._lock:
            # admitted-but-cold tenants sample 0 explicitly, so a
            # scrape distinguishes "evicted" from "unknown tenant"
            return [({"tenant": t}, float(sizes.get(t, 0)))
                    for t in sorted(self._tenants)]

    # -- lifecycle ----------------------------------------------------------
    def admit(self, tenant: str, models: Sequence[Any], *,
              priority: int = 0, pinned: bool = False,
              sizer: Optional[Callable[[], int]] = None,
              evictor: Optional[Callable[[], None]] = None
              ) -> _TenantState:
        """Admission control: register ``tenant`` iff its padded tables
        could ever fit the budget ALONE on an otherwise-empty device.
        Raises :class:`TableBudgetExceeded` otherwise — the same loud
        exit the sharded plane's replicated-upload refusal uses, and
        the same remedies apply (shard the table or raise the
        budget)."""
        tenant = str(tenant)
        expected = estimate_padded_bytes(models)
        if self.budget_bytes is not None \
                and expected > self.budget_bytes:
            raise TableBudgetExceeded(
                f"tenant {tenant!r}: padded factor tables need "
                f"{expected} bytes per device, over the host HBM "
                f"budget of {self.budget_bytes} bytes — this tenant "
                f"can NEVER fit; shard its tables over the mesh model "
                f"axis (factor_sharding='model'), shrink the vocab, "
                f"or raise PIO_TABLE_BUDGET_BYTES")
        st = _TenantState(tenant, expected, priority=priority,
                          pinned=pinned, sizer=sizer, evictor=evictor)
        with self._lock:
            self._tenants[tenant] = st
        return st

    def forget(self, tenant: str):
        with self._lock:
            self._tenants.pop(str(tenant), None)

    def touch(self, tenant: str):
        st = self._tenants.get(str(tenant))
        if st is not None:
            st.last_hit = time.monotonic()

    def pin(self, tenant: str, pinned: bool = True) -> bool:
        with self._lock:
            st = self._tenants.get(str(tenant))
            if st is None:
                return False
            st.pinned = bool(pinned)
            return True

    # -- accounting ---------------------------------------------------------
    def sizes(self) -> Dict[str, int]:
        """tenant -> per-device resident bytes, measured from the live
        device arrays: the tagged device-cache entries + residency
        payloads, plus each slot's sharded-table handles via its
        sizer — identity-DEDUPED, because a fold tick attaches the
        same device arrays to its ShardedTables and its residency
        payload (double-counting would inflate the gauge and make
        ensure_room evict neighbors that actually fit)."""
        arrays = device_cache.tenant_device_arrays()
        with self._lock:
            sizers = [(t, st.sizer) for t, st in self._tenants.items()
                      if st.sizer is not None]
        for t, sizer in sizers:
            try:
                arrays.setdefault(t, []).extend(sizer() or ())
            except Exception:
                logger.debug("tenant sizer failed for %s", t,
                             exc_info=True)
        out: Dict[str, int] = {}
        for t, arrs in arrays.items():
            seen = set()
            total = 0
            for a in arrs:
                if a is None or id(a) in seen:
                    continue
                seen.add(id(a))
                total += device_cache._device_nbytes(a)
            out[t] = total
        return out

    def resident_bytes(self) -> int:
        return sum(self.sizes().values())

    # -- policy -------------------------------------------------------------
    def _evictable(self, protect: str, sizes: Dict[str, int]
                   ) -> List[_TenantState]:
        """Cold candidates, coldest first: unpinned tenants (never
        ``protect``) holding resident bytes, ordered by (priority
        ascending, last_hit ascending) — low-priority idle tenants go
        first. Caller holds the lock."""
        cands = [st for t, st in self._tenants.items()
                 if t != protect and not st.pinned
                 and sizes.get(t, 0) > 0]
        cands.sort(key=lambda s: (s.priority, s.last_hit))
        return cands

    def ensure_room(self, tenant: str) -> int:
        """Make the budget hold once ``tenant``'s tables come resident:
        while (other tenants' resident bytes + this tenant's expected
        padded bytes) exceed the budget and a cold candidate exists,
        evict the coldest. Returns evictions performed. No-op without a
        budget.

        Best-effort by design: when every other tenant is pinned or
        hot, the upload proceeds and residency overshoots the
        manager's budget (logged loudly below). Note the per-UPLOAD
        backstop in ``cached_put_rows``/``ShardedTable.device`` reads
        only ``PIO_TABLE_BUDGET_BYTES`` — a ``HostConfig.budget_bytes``
        set programmatically governs admission + eviction policy
        here, not the put paths; deployments that want hard per-table
        refusal must set the env var (the runbook's recommendation)."""
        if self.budget_bytes is None:
            return 0
        tenant = str(tenant)
        evicted = 0
        for _ in range(len(self._tenants) + 1):
            sizes = self.sizes()
            with self._lock:
                st = self._tenants.get(tenant)
                need = st.expected_bytes if st is not None else 0
                projected = sum(b for t, b in sizes.items()
                                if t != tenant) \
                    + max(need, sizes.get(tenant, 0))
                if projected <= self.budget_bytes:
                    return evicted
                cands = self._evictable(tenant, sizes)
                if not cands:
                    logger.warning(
                        "tenant %s: projected residency %d bytes "
                        "exceeds the %d-byte budget and no unpinned "
                        "cold tenant is left to evict — overcommitting"
                        " (unpin a neighbor, raise the budget, or set "
                        "PIO_TABLE_BUDGET_BYTES for hard per-upload "
                        "refusal)", tenant, projected,
                        self.budget_bytes)
                    return evicted
                victim = cands[0].tenant
            self.evict(victim, reason="budget")
            evicted += 1
        return evicted

    def evict(self, tenant: str, reason: str = "operator") -> dict:
        """Evict one tenant's device residency back to host mirrors.
        Runs the host-provided evictor when set (quiesce + sharded
        handles + device-cache drop), else the plain device-cache
        drop. Returns {"tenant", "reason", "bytesFreed"}."""
        tenant = str(tenant)
        before = self.sizes().get(tenant, 0)
        with self._lock:
            st = self._tenants.get(tenant)
            evictor = st.evictor if st is not None else None
        if evictor is not None:
            evictor()
        else:
            device_cache.evict_tenant(tenant)
        freed = max(before - self.sizes().get(tenant, 0), 0)
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None:
                st.evictions += 1
        if self._c_evictions is not None:
            self._c_evictions.labels(tenant=tenant, reason=reason).inc()
        try:
            from predictionio_tpu.obs.flight import FLIGHT
            FLIGHT.record("tenant_eviction", tenant=tenant,
                          reason=reason, bytesFreed=int(freed))
        except Exception:
            logger.debug("tenant eviction flight record failed",
                         exc_info=True)
        logger.info("tenant %s evicted (%s): %d bytes freed",
                    tenant, reason, freed)
        return {"tenant": tenant, "reason": reason,
                "bytesFreed": int(freed)}

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> dict:
        sizes = self.sizes()
        with self._lock:
            tenants = {t: dict(st.snapshot(),
                               hbmBytes=int(sizes.get(t, 0)))
                       for t, st in self._tenants.items()}
        return {
            "budgetBytes": self.budget_bytes,
            "residentBytes": int(sum(sizes.values())),
            "tenants": tenants,
        }
