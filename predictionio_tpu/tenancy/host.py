"""ServingHost: route queries by app/engine key to per-tenant slots.

One process, one accelerator, many engines. Each tenant is a full
:class:`~predictionio_tpu.serving.server.EngineServer` slot — its own
micro-batcher/pipelined executor, canary controller, rollback anchors,
scheduler attachment and tenant-namespaced result-cache view — loaded
from its own engine instance and addressed as
``/engines/<tenant>/...``. What the slots SHARE is the device: the
process-wide compile-plane bucket ladder (two tenants with identical
shapes reuse the same AOT executables — the packing payoff), the
persistent XLA cache, and the HBM the
:class:`~predictionio_tpu.tenancy.budget.HBMBudgetManager` arbitrates.

Isolation contracts (tested by tests/test_tenancy.py):

- a query for tenant A can never be answered from tenant B's cached
  result (tenant-prefixed result-cache keys, ISSUE 15 satellite);
- tenant B's eviction never touches tenant A's models, caches,
  canary state or last-known-good pins;
- eviction never fires mid-dispatch on an in-flight window: the
  evictor quiesces the slot first (the PR 13 snapshot discipline
  extended to residency handles) and skips the drop on timeout;
- an evicted tenant's next query re-uploads from host mirrors and
  serves byte-identical rankings (the mirrors are the truth).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from predictionio_tpu.obs import FLIGHT, MetricsRegistry, fleet, \
    get_registry
from predictionio_tpu.obs.tenantctx import register_tenant, tenant_scope
from predictionio_tpu.serving.server import EngineServer, ServerConfig
from predictionio_tpu.tenancy import props as tenant_props
from predictionio_tpu.tenancy.auth import AccessKeyGate, auth_enabled
from predictionio_tpu.tenancy.budget import HBMBudgetManager, _iter_tables
from predictionio_tpu.utils import device_cache
from predictionio_tpu.utils.http import (HttpServer, Request, Response,
                                         Router)

logger = logging.getLogger(__name__)

#: characters a tenant key must not contain: path separators (the key
#: is a URL segment) and the result-cache namespace separator
_FORBIDDEN = set("/\x1f\n\r")


def _check_key(key: str) -> str:
    key = str(key)
    if not key or _FORBIDDEN.intersection(key):
        raise ValueError(f"invalid tenant key {key!r}")
    return key


@dataclass
class TenantSpec:
    """One tenant: which engine instance to serve, and its packing
    policy. ``key`` is the routing segment (conventionally
    ``<app>-<engine>`` or the engine id). Higher ``priority`` evicts
    later; ``pinned`` never auto-evicts (operator evict still works)."""
    key: str
    engine_id: Optional[str] = None
    engine_version: str = "0"
    engine_variant: str = "engine.json"
    engine_instance_id: Optional[str] = None
    priority: int = 0
    pinned: bool = False
    #: full per-slot ServerConfig override; None derives one from the
    #: engine coordinates above with stock serving defaults
    server_config: Optional[ServerConfig] = None


class TenantSlot:
    """One admitted tenant: its engine server plus the in-flight gate
    the evictor quiesces against."""

    def __init__(self, spec: TenantSpec, server: EngineServer):
        self.key = spec.key
        self.spec = spec
        self.server = server
        self.scheduler = None
        self.scheduler_config = None
        self.requests = 0
        self.errors = 0
        self.admitted_at = time.time()
        #: True when this tenant's tables may not be resident (fresh
        #: admission or post-eviction) — the next query calls
        #: ensure_room before dispatching
        self.cold = True
        self._cond = threading.Condition()
        self._inflight = 0
        self._evicting = False

    # -- the in-flight gate --------------------------------------------------
    @contextlib.contextmanager
    def serving(self):
        """Count one request in flight; entry waits out an active
        eviction (eviction windows are bounded by the quiesce
        timeout)."""
        with self._cond:
            while self._evicting:
                self._cond.wait(timeout=1.0)
            self._inflight += 1
        try:
            yield
        finally:
            with self._cond:
                self._inflight -= 1
                if self._inflight <= 0:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def quiesced(self, timeout_s: float):
        """Block new requests and wait for in-flight ones to drain;
        yields True when drained (the evictor may drop residency) or
        False on timeout (it must NOT — an in-flight window's inputs
        stay pinned)."""
        with self._cond:
            self._evicting = True
            deadline = time.monotonic() + timeout_s
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(timeout=left)
            drained = self._inflight == 0
        try:
            yield drained
        finally:
            with self._cond:
                self._evicting = False
                self._cond.notify_all()

    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def status(self) -> dict:
        srv = self.server
        return {
            "tenant": self.key,
            "engineId": self.spec.engine_id,
            "engineVersion": self.spec.engine_version,
            "engineVariant": self.spec.engine_variant,
            "modelVersion": srv.model_version,
            "lastGoodVersion": srv.last_good_version,
            "requests": self.requests,
            "errors": self.errors,
            "inflight": self.inflight(),
            "cold": self.cold,
            "scheduler": self.scheduler is not None,
            "canary": srv.canary.stats(),
            "modelSharding": srv._model_sharding(),
            "admittedAt": self.admitted_at,
        }


@dataclass
class HostConfig:
    ip: str = "0.0.0.0"
    port: int = 8100
    #: per-device HBM table budget for the whole host; None reads the
    #: enforced PIO_TABLE_BUDGET_BYTES (None there too = accounting
    #: only)
    budget_bytes: Optional[int] = None
    #: one shared result cache for every tenant (tenant-namespaced
    #: keys); budgets are host-wide so a hot tenant can use the pool
    result_cache: bool = True
    result_cache_max_entries: int = 8192
    result_cache_max_bytes: int = 64 << 20
    #: how long an eviction may wait for a slot's in-flight windows
    #: before giving up (the drop is skipped, never forced)
    evict_quiesce_timeout_s: float = 10.0


class ServingHost:
    def __init__(self, config: Optional[HostConfig] = None):
        self.config = config or HostConfig()
        self._lock = threading.RLock()
        self.slots: Dict[str, TenantSlot] = {}
        self.start_time = time.time()
        self.metrics = MetricsRegistry(parent=get_registry())
        self.budget = HBMBudgetManager(self.config.budget_bytes,
                                       registry=self.metrics)
        self._c_requests = self.metrics.counter(
            "pio_tenant_requests_total",
            "Queries routed to each serving tenant",
            labelnames=("tenant",))
        self.metrics.gauge_func(
            "pio_host_tenants",
            "Tenant slots admitted on this serving host",
            lambda: len(self.slots))
        from predictionio_tpu.serving import result_cache as RC
        self.result_cache = None
        if self.config.result_cache and RC.cache_enabled():
            self.result_cache = RC.ResultCache(
                max_entries=self.config.result_cache_max_entries,
                max_bytes=self.config.result_cache_max_bytes,
                metrics=self.metrics)
        self.server: Optional[HttpServer] = None
        self._fleet_id: Optional[str] = None
        # per-tenant traffic EWMA state: key -> [t, requests, ewma]
        self._traffic: Dict[str, list] = {}
        # placement generation fence (ISSUE 18): key -> the newest
        # generation a control action (admit/remove) named. A stale
        # controller retry or a router holding an old placement can
        # never act or serve against a superseded generation. Kept
        # monotonic even after removal, so a delayed re-admit of an
        # already-migrated tenant is refused.
        self._placement_gen: Dict[str, int] = {}
        # access-key gate (PIO_AUTH=on, ISSUE 18 satellite): armed at
        # construction so the per-request cost is one None-check
        self._auth = AccessKeyGate() if auth_enabled() else None
        # per-host fold-tick fairness gate, created with the first
        # attached scheduler (online/scheduler.FoldTickGate)
        self.tick_gate = None
        self.router = self._build_router()

    # -- tenant lifecycle ---------------------------------------------------
    def _slot_config(self, spec: TenantSpec) -> ServerConfig:
        if spec.server_config is not None:
            return spec.server_config
        return ServerConfig(
            engine_instance_id=spec.engine_instance_id,
            engine_id=spec.engine_id,
            engine_version=spec.engine_version,
            engine_variant=spec.engine_variant)

    def add_tenant(self, spec: TenantSpec, engine=None,
                   engine_params=None) -> TenantSlot:
        """Load + admit one tenant. The load happens OUTSIDE the host
        lock (model deserialization can be slow; other tenants keep
        serving); admission control runs before the slot becomes
        routable — a tenant whose padded tables can never fit raises
        :class:`TableBudgetExceeded` and leaves no slot behind."""
        key = _check_key(spec.key)
        register_tenant(key)   # bounded metric-label cardinality
        self._overlay_props(spec)
        with self._lock:
            if key in self.slots:
                raise ValueError(f"tenant {key!r} already admitted")
        server = EngineServer(self._slot_config(spec), engine=engine,
                              engine_params=engine_params, tenant=key,
                              shared_result_cache=self.result_cache)
        with device_cache.tenant_scope(key):
            server.load()
        slot = TenantSlot(spec, server)
        try:
            self.budget.admit(
                key, server.models, priority=spec.priority,
                pinned=spec.pinned,
                sizer=lambda s=slot: self._sharded_devs(s),
                evictor=lambda s=slot: self._evict_slot(s))
        except Exception:
            server.stop()
            raise
        with self._lock:
            self.slots[key] = slot
        FLIGHT.record("tenant_admitted", tenant=key,
                      model_version=server.model_version,
                      expectedPaddedBytes=self.budget.snapshot()
                      ["tenants"][key]["expectedPaddedBytes"])
        logger.info("tenant %s admitted (instance %s)", key,
                    server.model_version)
        self._publish_roster()
        return slot

    def _overlay_props(self, spec: TenantSpec):
        """Overlay the durable per-tenant props (tenancy/props.py) on
        the static spec: a ``pio tenants pin`` taken before a host
        restart must survive it (ISSUE 18 satellite)."""
        stored = tenant_props.load_props(spec.key)
        if not stored:
            return
        if "priority" in stored:
            spec.priority = int(stored["priority"])
        if "pinned" in stored:
            spec.pinned = bool(stored["pinned"])

    def admit_server(self, spec: TenantSpec,
                     server: EngineServer) -> TenantSlot:
        """Admit a pre-built, already-loaded :class:`EngineServer` as a
        tenant slot (bench/test path; production slots go through
        :meth:`add_tenant`, which loads from the engine-instance
        store). The server must have been constructed with
        ``tenant=spec.key`` so its uploads carry the attribution tag —
        refused otherwise (untagged uploads would make this tenant
        unevictable AND unaccounted)."""
        key = _check_key(spec.key)
        if server.tenant != key:
            raise ValueError(
                f"server.tenant {server.tenant!r} != spec.key {key!r}: "
                f"construct the EngineServer with tenant=<key>")
        register_tenant(key)
        self._overlay_props(spec)
        with self._lock:
            if key in self.slots:
                raise ValueError(f"tenant {key!r} already admitted")
        slot = TenantSlot(spec, server)
        self.budget.admit(
            key, server.models, priority=spec.priority,
            pinned=spec.pinned,
            sizer=lambda s=slot: self._sharded_devs(s),
            evictor=lambda s=slot: self._evict_slot(s))
        with self._lock:
            self.slots[key] = slot
        self._publish_roster()
        return slot

    def remove_tenant(self, key: str) -> bool:
        with self._lock:
            slot = self.slots.pop(key, None)
        if slot is None:
            return False
        if slot.scheduler is not None:
            try:
                slot.scheduler.stop()
            except Exception:
                logger.exception("tenant %s scheduler stop failed", key)
        self.budget.evict(key, reason="remove")
        self.budget.forget(key)
        slot.server.stop()
        FLIGHT.record("tenant_removed", tenant=key)
        self._publish_roster()
        return True

    def attach_scheduler(self, key: str, config, **kw):
        """Attach a fold-in scheduler to one tenant slot — every fold
        tick runs under the tenant's device attribution scope, and its
        publishes hot-swap only this slot. All schedulers on one host
        share the host's :class:`FoldTickGate`, so contending tenants
        round-robin the device by staleness instead of FIFO thread
        wakeup (ISSUE 18 satellite)."""
        from predictionio_tpu.online.scheduler import (FoldTickGate,
                                                       attach_scheduler)
        with self._lock:
            if self.tick_gate is None:
                self.tick_gate = FoldTickGate(registry=self.metrics)
            gate = self.tick_gate
        kw.setdefault("tick_gate", gate)
        slot = self._slot(key)
        sched = attach_scheduler(slot.server, config, tenant=key, **kw)
        slot.scheduler = sched
        slot.scheduler_config = config
        self._publish_roster()
        return sched

    # -- eviction mechanism -------------------------------------------------
    @staticmethod
    def _sharded_tables(slot: TenantSlot):
        from predictionio_tpu.parallel.sharded_table import is_sharded
        return [t for t in _iter_tables(slot.server.models)
                if is_sharded(t)]

    def _sharded_devs(self, slot: TenantSlot) -> list:
        """The slot's resident ShardedTable device handles — arrays,
        not bytes: the budget manager identity-dedupes them against
        the fold-residency payloads carrying the same handles."""
        return [t._dev for t in self._sharded_tables(slot)
                if t._dev is not None]

    def _evict_slot(self, slot: TenantSlot):
        """The per-slot evictor the budget manager calls: quiesce the
        in-flight gate, then drop the tenant's device-cache entries,
        residency slots and sharded-table handles. On quiesce timeout
        the drop is SKIPPED — an in-flight window must complete against
        the handles it snapshotted (PR 13 semantics; its closures pin
        the arrays anyway, so a forced drop would only lie about
        freed bytes)."""
        with slot.quiesced(self.config.evict_quiesce_timeout_s) \
                as drained:
            if not drained:
                logger.warning(
                    "tenant %s eviction skipped: %d windows still in "
                    "flight after %.1fs", slot.key, slot.inflight(),
                    self.config.evict_quiesce_timeout_s)
                return
            device_cache.evict_tenant(slot.key)
            for t in self._sharded_tables(slot):
                t.drop_device()
            slot.cold = True

    def evict_tenant(self, key: str, reason: str = "operator") -> dict:
        self._slot(key)   # KeyError on unknown tenant
        return self.budget.evict(key, reason=reason)

    # -- routing ------------------------------------------------------------
    def _slot(self, key: str) -> TenantSlot:
        slot = self.slots.get(key)
        if slot is None:
            raise KeyError(key)
        return slot

    def _tenant_query(self, req: Request) -> Response:
        key = req.path_args[0]
        slot = self.slots.get(key)
        if slot is None:
            return Response(404, {"message": f"unknown tenant {key!r}"})
        if self._auth is not None:
            denied = self._auth.check(
                req, getattr(slot.server.config, "accesskey", None)
                or None)
            if denied is not None:
                return denied
        # generation fence (ISSUE 18): a router that attaches the
        # placement generation it routed by gets an honest 409 when
        # that placement has been superseded — refresh, don't serve
        gen_hdr = req.headers.get("x-pio-placement-gen") \
            if req.headers else None
        if gen_hdr is not None:
            try:
                if int(gen_hdr) < self._placement_gen.get(key, 0):
                    return Response(409, {
                        "message": "stale placement route",
                        "tenant": key,
                        "generation": self._placement_gen.get(key, 0)})
            except (TypeError, ValueError):
                pass
        # tenant attribution scope (ISSUE 17): everything this request
        # touches on the way down — budget room-making, slowlog
        # captures, flight records, trace roots, device dispatch — is
        # stamped/booked under this tenant
        with tenant_scope(key):
            self._c_requests.labels(tenant=key).inc()
            slot.requests += 1
            self.budget.touch(key)
            if slot.cold:
                # fresh admission or post-eviction readmission: make
                # the budget hold before this tenant's tables come
                # (back) resident — evicts the coldest neighbors if
                # needed
                self.budget.ensure_room(key)
                slot.cold = False
            req.path = "/queries.json"
            with slot.serving():
                resp = slot.server.router.dispatch(req)
        if resp.status >= 500:
            slot.errors += 1
        return resp

    def _delegate(self, req: Request) -> Response:
        """Forward ``/engines/<key>/<endpoint>`` to the slot server's
        own router (stats, metrics, health, reload, ...)."""
        key = req.path_args[0]
        slot = self.slots.get(key)
        if slot is None:
            return Response(404, {"message": f"unknown tenant {key!r}"})
        req.path = req.path[len(f"/engines/{key}"):]
        with tenant_scope(key), slot.serving():
            return slot.server.router.dispatch(req)

    # -- host surfaces ------------------------------------------------------
    def _tenants_block(self) -> dict:
        budget = self.budget.snapshot()
        out = {}
        with self._lock:
            slots = list(self.slots.values())
        for slot in slots:
            entry = slot.status()
            entry.update(budget["tenants"].get(slot.key, {}))
            out[slot.key] = entry
        return out

    def _stats(self, req: Request) -> Response:
        budget = self.budget.snapshot()
        with self._lock:
            total = sum(s.requests for s in self.slots.values())
        out = {
            "role": "serving_host",
            "startTime": self.start_time,
            "requestCount": total,
            "tenants": self._tenants_block(),
            "budget": {k: budget[k]
                       for k in ("budgetBytes", "residentBytes")},
        }
        if self.result_cache is not None:
            out["resultCache"] = self.result_cache.stats()
        try:
            from predictionio_tpu.compile.aot import get_aot
            out["aot"] = get_aot().snapshot()
        except Exception:
            logger.debug("aot stats unavailable", exc_info=True)
        return Response(200, out)

    def _tenants(self, req: Request) -> Response:
        return Response(200, {"tenants": self._tenants_block()})

    def _tenant_evict(self, req: Request) -> Response:
        key = req.path_args[0]
        try:
            return Response(200, self.evict_tenant(key))
        except KeyError:
            return Response(404, {"message": f"unknown tenant {key!r}"})

    def _tenant_pin(self, req: Request) -> Response:
        key = req.path_args[0]
        pinned = not req.path.endswith("/unpin")
        if not self.budget.pin(key, pinned):
            return Response(404, {"message": f"unknown tenant {key!r}"})
        # persist the pin as a durable tenant prop so a host restart
        # re-admits with it (ISSUE 18 satellite); the in-memory ledger
        # flip above is the serving truth either way
        persisted = tenant_props.save_props(key, pinned=pinned)
        slot = self.slots.get(key)
        if slot is not None:
            slot.spec.pinned = pinned
        self._publish_roster()
        return Response(200, {"tenant": key, "pinned": pinned,
                              "persisted": persisted is not None})

    # -- control plane (ISSUE 18): remote admit/remove + roster -------------
    _SCHED_FIELDS = ("app_name", "channel_name", "event_names",
                     "max_deltas", "max_staleness_s", "poll_interval_s",
                     "tail_batch_limit", "filtered_reads")

    def _sched_dict(self, cfg) -> dict:
        out = {}
        for k in self._SCHED_FIELDS:
            v = getattr(cfg, k, None)
            if v is not None:
                out[k] = list(v) if isinstance(v, tuple) else v
        return out

    def _publish_roster(self):
        """Re-publish this host's member record with its full tenant
        roster (spec + generation + scheduler config). The roster must
        live ON the record, refreshed at every admit/remove/pin: when
        this process is SIGKILLed, the corpse record is the failover
        controller's only source for which tenants to re-place and how
        to rebuild them (engine coords -> registry lineage, scheduler
        config -> fold-tail catch-up)."""
        with self._lock:
            fid = self._fleet_id
            slots = list(self.slots.values())
            gens = dict(self._placement_gen)
        if not fid:
            return
        roster = {}
        for slot in slots:
            spec = slot.spec
            entry = {
                "engineId": spec.engine_id,
                "engineVersion": spec.engine_version,
                "engineVariant": spec.engine_variant,
                "engineInstanceId": spec.engine_instance_id,
                "priority": spec.priority,
                "pinned": spec.pinned,
                "generation": gens.get(slot.key, 0),
            }
            if slot.scheduler_config is not None:
                entry["scheduler"] = self._sched_dict(
                    slot.scheduler_config)
            roster[slot.key] = entry
        fleet.update_member(fid, {"tenants": roster})

    def _fence(self, key: str, gen) -> Optional[Response]:
        """409 when ``gen`` is older than the newest generation a
        control action named for this tenant; otherwise records it."""
        try:
            gen = int(gen or 0)
        except (TypeError, ValueError):
            return Response(400, {"message": "generation must be int"})
        with self._lock:
            cur = self._placement_gen.get(key, 0)
            if gen < cur:
                return Response(409, {
                    "message": "stale placement generation",
                    "tenant": key, "generation": cur})
            self._placement_gen[key] = gen
        return None

    def _tenant_admit(self, req: Request) -> Response:
        """``POST /tenants/<key>/admit`` — the controller's remote
        admission path. Body: engine coordinates (+ optional priority/
        pinned/scheduler config) and the placement ``generation``.
        Loads from registry lineage, AOT-warms before the slot becomes
        routable (add_tenant -> EngineServer.load), attaches the fold
        scheduler when configured (its cursor resumes from the
        published lineage — the fold-tail catch-up), and refuses
        honestly on budget exhaustion (409, the controller re-plans)."""
        key = req.path_args[0]
        try:
            body = req.json() or {}
        except ValueError:
            return Response(400, {"message": "body must be JSON"})
        fence = self._fence(key, body.get("generation"))
        if fence is not None:
            return fence
        with self._lock:
            if key in self.slots:
                return Response(200, {"tenant": key,
                                      "alreadyAdmitted": True})
        spec = TenantSpec(
            key=key,
            engine_id=body.get("engineId"),
            engine_version=str(body.get("engineVersion") or "0"),
            engine_variant=body.get("engineVariant") or "engine.json",
            engine_instance_id=body.get("engineInstanceId"),
            priority=int(body.get("priority") or 0),
            pinned=bool(body.get("pinned")))
        from predictionio_tpu.tenancy.budget import TableBudgetExceeded
        try:
            self.add_tenant(spec)
        except TableBudgetExceeded as e:
            return Response(409, {"message": f"admission refused: {e}",
                                  "tenant": key})
        except ValueError as e:
            return Response(409, {"message": str(e), "tenant": key})
        except Exception as e:
            logger.exception("tenant %s remote admission failed", key)
            return Response(500, {"message": f"admission failed: {e}",
                                  "tenant": key})
        sched = body.get("scheduler")
        if isinstance(sched, dict) and sched.get("app_name"):
            try:
                from predictionio_tpu.online.registry import \
                    ModelVersionRegistry
                from predictionio_tpu.online.scheduler import \
                    SchedulerConfig
                cfg = SchedulerConfig(**{
                    k: sched[k] for k in self._SCHED_FIELDS
                    if k in sched})
                self.attach_scheduler(
                    key, cfg, registry=ModelVersionRegistry()).start()
            except Exception:
                # the tenant serves; a broken fold attachment is an
                # incident, not a failed admission
                logger.exception("tenant %s scheduler attach failed",
                                 key)
        slot = self.slots.get(key)
        return Response(200, {
            "tenant": key,
            "generation": self._placement_gen.get(key, 0),
            "modelVersion": slot.server.model_version if slot else None,
            "scheduler": bool(slot and slot.scheduler is not None)})

    def _tenant_remove(self, req: Request) -> Response:
        """``POST /tenants/<key>/remove`` — generation-fenced removal,
        the last step of a planned migration (the target host owns the
        newer generation by then, so a stale retry cannot re-kill)."""
        key = req.path_args[0]
        try:
            body = req.json() or {}
        except ValueError:
            body = {}
        fence = self._fence(key, body.get("generation"))
        if fence is not None:
            return fence
        if not self.remove_tenant(key):
            return Response(404, {"message": f"unknown tenant {key!r}"})
        return Response(200, {"tenant": key, "removed": True,
                              "generation":
                                  self._placement_gen.get(key, 0)})

    def _placement(self, req: Request) -> Response:
        """``GET /placement.json`` — the host's placement truth: per
        tenant the generation, spec and budget row the controller
        plans against."""
        budget = self.budget.snapshot()
        with self._lock:
            slots = list(self.slots.values())
            gens = dict(self._placement_gen)
        tenants = {}
        for slot in slots:
            spec = slot.spec
            tenants[slot.key] = {
                "generation": gens.get(slot.key, 0),
                "engineId": spec.engine_id,
                "engineVersion": spec.engine_version,
                "engineVariant": spec.engine_variant,
                "engineInstanceId": spec.engine_instance_id,
                "priority": spec.priority,
                "pinned": spec.pinned,
                "cold": slot.cold,
                "scheduler": slot.scheduler is not None,
                "expectedPaddedBytes": budget["tenants"].get(
                    slot.key, {}).get("expectedPaddedBytes", 0),
            }
        return Response(200, {
            "memberId": self._fleet_id,
            "budgetBytes": budget["budgetBytes"],
            "residentBytes": budget["residentBytes"],
            "generations": gens,
            "tenants": tenants,
        })

    def _metrics(self, req: Request) -> Response:
        """One scrape for the whole host: the host/process families
        plus every slot registry's OWN families re-labeled with
        ``tenant`` (ISSUE 17) — so serve histograms, canary counters
        and cache stats from different slots are distinct series under
        shared family names, and the fleet federator's ``{role,pid}``
        relabeling stacks on top."""
        from predictionio_tpu.obs.fleet import merge_scrapes
        from predictionio_tpu.utils.prometheus import CONTENT_TYPE
        with self._lock:
            slots = list(self.slots.values())
        parts = [(self.metrics.render(), {})]
        for slot in slots:
            try:
                parts.append(
                    (slot.server.metrics.render(include_parent=False),
                     {"tenant": slot.key}))
            except Exception:
                logger.debug("tenant %s metrics render failed",
                             slot.key, exc_info=True)
        return Response(200, merge_scrapes(parts),
                        content_type=CONTENT_TYPE)

    def _health(self, req: Request) -> Response:
        """Worst-of rollup across tenant slots' SLO engines. Each
        slot's breach transitions are noted under its tenant scope, so
        a breached slot captures an incident bundle naming THAT tenant
        (and only its forensics slice) — the noisy neighbor stays out
        of the victim's postmortem and vice versa."""
        from predictionio_tpu.obs import health_response
        rank = {"ok": 0, "burning": 1, "no_data": 0, "breached": 2}
        worst, tenants = "ok", {}
        with self._lock:
            slots = list(self.slots.values())
        for slot in slots:
            with tenant_scope(slot.key):
                h = health_response(slot.server.slo, extra={
                    "modelVersion": slot.server.model_version,
                    "tenant": slot.key})
                try:
                    slot.server._note_slo_breaches(h)
                except Exception:
                    logger.debug("tenant %s breach note failed",
                                 slot.key, exc_info=True)
            tenants[slot.key] = h
            if rank.get(h.get("status"), 0) > rank.get(worst, 0):
                worst = h["status"]
        return Response(200, {"status": worst, "tenants": tenants})

    # -- per-tenant signals (ISSUE 17) --------------------------------------
    def _traffic_ewma(self, key: str, requests: int) -> float:
        """Lazily-updated per-tenant request-rate EWMA (alpha 0.3 per
        observation window), advanced on each signals read from the
        slot's cumulative request counter."""
        now = time.monotonic()
        st = self._traffic.get(key)
        if st is None:
            self._traffic[key] = [now, requests, 0.0]
            return 0.0
        last_t, last_n, ewma = st
        dt = now - last_t
        if dt >= 0.2:   # too-close reads would amplify quantization
            inst = max(0.0, requests - last_n) / dt
            ewma = inst if ewma == 0.0 else 0.7 * ewma + 0.3 * inst
            self._traffic[key] = [now, requests, ewma]
        return ewma

    def tenant_signals(self) -> dict:
        """The ``GET /tenants/signals.json`` body: one row per tenant
        with its traffic, latency, burn, memory and device-time
        attribution — the single surface that answers "who is eating
        the device" (docs/operations.md)."""
        from predictionio_tpu.obs import costmon
        from predictionio_tpu.obs.metrics import get_registry
        budget = self.budget.snapshot()
        dev_share = costmon.tenant_device_time_share()
        occ_share = costmon.tenant_occupancy_shares()
        # per-tenant serve readback bytes (ISSUE 19): the packed d2h
        # plane attributes every fetched byte to the obs-plane tenant
        # context, so the bill decomposes transfer cost too
        d2h_bytes = {}
        fam = get_registry().get("pio_tenant_serve_d2h_bytes_total")
        if fam is not None:
            for labels, value in fam.samples():
                if labels:
                    d2h_bytes[labels.get("tenant", "")] = int(value)
        with self._lock:
            slots = list(self.slots.values())
        tenants = {}
        for slot in slots:
            srv = slot.server
            row = {
                "requests": slot.requests,
                "errors": slot.errors,
                "trafficEwmaRps": round(
                    self._traffic_ewma(slot.key, slot.requests), 3),
                "deviceTimeShare": dev_share.get(slot.key, 0.0),
                "occupancyShare": occ_share.get(slot.key, 0.0),
                "serveD2hBytes": d2h_bytes.get(slot.key, 0),
                "modelStalenessS": srv.model_staleness_s(),
                "modelVersion": srv.model_version,
            }
            b = budget["tenants"].get(slot.key, {})
            row["hbmBytes"] = b.get("hbmBytes", 0)
            row["evictions"] = b.get("evictions", 0)
            fam = srv.metrics.get("pio_engine_query_seconds")
            if fam is not None and getattr(fam, "count", 0):
                p50, p99 = fam.percentile(50), fam.percentile(99)
                row["serveP50Ms"] = round(p50 * 1000.0, 3) \
                    if p50 is not None else None
                row["serveP99Ms"] = round(p99 * 1000.0, 3) \
                    if p99 is not None else None
            else:
                row["serveP50Ms"] = row["serveP99Ms"] = None
            try:
                h = srv.slo.evaluate()
                row["sloStatus"] = h["status"]
                serve = next((s for s in h["slo"]
                              if s["name"] == "serve_p99"), {})
                row["burnFast"] = serve.get("burnFast")
                row["burnSlow"] = serve.get("burnSlow")
            except Exception:
                row["sloStatus"] = "no_data"
                row["burnFast"] = row["burnSlow"] = None
            tenants[slot.key] = row
        return {
            "tenants": tenants,
            # the full attribution maps, "" = untenanted process work:
            # the smoke check asserts sum(deviceTimeShare) <= 1.0 over
            # THESE (per-slot rows omit departed tenants' residue)
            "deviceTimeShare": dev_share,
            "occupancyShare": occ_share,
            "budgetBytes": budget["budgetBytes"],
            "residentBytes": budget["residentBytes"],
        }

    def _signals(self, req: Request) -> Response:
        return Response(200, self.tenant_signals())

    def _status_page(self, req: Request) -> Response:
        return Response(200, {
            "role": "serving_host",
            "tenants": sorted(self.slots),
            "budget": self.budget.snapshot()["budgetBytes"],
        })

    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self._status_page)
        r.add("POST", "/engines/<key>/queries.json", self._tenant_query)
        for ep in ("stats.json", "metrics", "health.json",
                   "plugins.json", "slow.json", "flight.json",
                   "traces.json"):
            r.add("GET", f"/engines/<key>/{ep}", self._delegate)
        r.add("POST", "/engines/<key>/reload", self._delegate)
        r.add("GET", "/engines/<key>/reload", self._delegate)
        r.add("GET", "/stats.json", self._stats)
        r.add("GET", "/tenants.json", self._tenants)
        r.add("GET", "/tenants/signals.json", self._signals)
        r.add("GET", "/placement.json", self._placement)
        r.add("POST", "/tenants/<key>/evict", self._tenant_evict)
        r.add("POST", "/tenants/<key>/admit", self._tenant_admit)
        r.add("POST", "/tenants/<key>/remove", self._tenant_remove)
        r.add("POST", "/tenants/<key>/pin", self._tenant_pin)
        r.add("POST", "/tenants/<key>/unpin", self._tenant_pin)
        r.add("GET", "/metrics", self._metrics)
        r.add("GET", "/health.json", self._health)
        return r

    # -- lifecycle ----------------------------------------------------------
    def start(self, background: bool = True) -> "ServingHost":
        from predictionio_tpu.obs import profiler
        profiler.ensure_started()
        srv = HttpServer(self.router, self.config.ip, self.config.port)
        self.server = srv

        def _bound(s):
            self.config.port = s.port
            fid = fleet.register_member("serving_host", port=s.port,
                                        host=self.config.ip)
            with self._lock:
                self._fleet_id = fid
            # the record now exists with the advertised url; stamp the
            # current roster on it so a crash any time after bind
            # leaves a forensically-complete corpse
            self._publish_roster()
            logger.info("Serving host started on %s:%d (%d tenants)",
                        self.config.ip, s.port, len(self.slots))

        srv.on_bound = _bound
        srv.start(background=background)
        return self

    def stop(self):
        with self._lock:
            fleet_id = self._fleet_id
            self._fleet_id = None
            keys = list(self.slots)
        fleet.deregister_member(fleet_id)
        if self.server:
            self.server.stop()
            self.server = None
        for key in keys:
            try:
                self.remove_tenant(key)
            except Exception:
                logger.exception("tenant %s removal failed on stop", key)
