"""Access-key gate for multi-tenant query routing (ISSUE 18 satellite).

Original PredictionIO authenticated EVERY surface — the event API
checked ``accessKey`` against the AccessKeys/Apps metadata tables on
each request (PAPER.md §1). Our event server kept that; the serving
path never had it, because a single-engine server is usually deployed
behind something that already did. A multi-tenant host is different:
one port fronts many tenants, and an unauthenticated
``/engines/<tenant>/queries.json`` lets any client query any tenant.

``PIO_AUTH=on`` arms this gate on the ServingHost router. The contract:

- The key rides the ``accessKey`` query parameter (the classic
  PredictionIO client convention) or the ``X-PIO-Access-Key`` header.
- It must resolve through the AccessKeys DAO to a live App row. A
  slot whose ``ServerConfig.accesskey`` names a specific key
  additionally requires an exact match — that is the per-tenant
  scoping knob (each tenant's app has its own key).
- Failures 401 with an honest body naming WHAT was wrong (missing vs
  unknown vs wrong-tenant), never a bare status.

The hot path must stay sub-µs: DAO hits are cached per key with a TTL
(``PIO_AUTH_CACHE_TTL_S``, default 30s), so steady-state validation is
one dict lookup and a monotonic compare. Revocation latency equals the
TTL — the honest trade, documented in operations.md.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional, Tuple

from predictionio_tpu.utils.http import Request, Response

logger = logging.getLogger(__name__)

HEADER = "x-pio-access-key"


def auth_enabled() -> bool:
    return os.environ.get("PIO_AUTH", "").strip().lower() in (
        "on", "1", "true", "yes")


def cache_ttl_s() -> float:
    try:
        return max(0.0, float(os.environ.get("PIO_AUTH_CACHE_TTL_S",
                                             "30.0")))
    except (TypeError, ValueError):
        return 30.0


def _deny(message: str) -> Response:
    import json
    return Response(401, json.dumps({"message": message}),
                    content_type="application/json")


class AccessKeyGate:
    """TTL-cached access-key validator.

    ``check(req, expected_key)`` returns None on success or a 401
    ``Response`` to short-circuit the router with. The cache maps
    key -> (appid_or_None, expiry): a *negative* entry (None appid)
    is cached too, so a flood of bad-key requests costs one DAO read
    per TTL, not one per request."""

    def __init__(self, ttl_s: Optional[float] = None):
        self._ttl_s = cache_ttl_s() if ttl_s is None else float(ttl_s)
        self._lock = threading.Lock()
        self._cache: Dict[str, Tuple[Optional[int], float]] = {}

    @staticmethod
    def _extract(req: Request) -> Optional[str]:
        key = (req.params or {}).get("accessKey")
        if key:
            return str(key)
        key = (req.headers or {}).get(HEADER)
        return str(key) if key else None

    def _resolve(self, key: str) -> Optional[int]:
        """appid for a valid key, None for an unknown/orphaned one.
        DAO errors deny (fail-closed: an unreachable metadata store
        must not open every tenant to every caller)."""
        from predictionio_tpu.data.storage.registry import Storage
        try:
            ak = Storage.get_meta_data_access_keys().get(key)
            if ak is None:
                return None
            app = Storage.get_meta_data_apps().get(ak.appid)
            return ak.appid if app is not None else None
        except Exception:
            logger.warning("auth: access-key lookup failed; denying",
                           exc_info=True)
            return None

    def _lookup(self, key: str) -> Optional[int]:
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and hit[1] > now:
                return hit[0]
        appid = self._resolve(key)
        with self._lock:
            if len(self._cache) >= 4096:
                # bounded: an attacker spraying random keys must not
                # grow the cache without limit
                self._cache.clear()
            self._cache[key] = (appid, now + self._ttl_s)
        return appid

    def check(self, req: Request,
              expected_key: Optional[str] = None) -> Optional[Response]:
        key = self._extract(req)
        if not key:
            return _deny("access key required: pass ?accessKey= or the "
                         "X-PIO-Access-Key header (PIO_AUTH=on)")
        if expected_key and key != expected_key:
            return _deny("access key is not authorized for this tenant")
        if self._lookup(key) is None:
            return _deny("access key is invalid or its app is gone")
        return None

    def invalidate(self, key: Optional[str] = None):
        with self._lock:
            if key is None:
                self._cache.clear()
            else:
                self._cache.pop(key, None)
