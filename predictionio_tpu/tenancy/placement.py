"""Placement planning: pure decisions over fleet tenant state (ISSUE 18).

This module is the PLANNER half of the fleet tenant control plane —
deliberately free of clocks, HTTP, and storage so every decision is a
deterministic function of its inputs and the golden-table tests in
tests/test_placement.py pin the policy down exactly. The controller
(tenancy/controller.py) owns observation and actuation; this module
answers one question: given hosts with HBM budgets and tenants with
footprints/priorities/traffic, WHERE does each tenant go?

Inputs mirror the PR 17 signals surface: a tenant's cost is its
``pio_engine_hbm_bytes`` footprint (the budget ledger's padded-bytes
estimate), its heat is the traffic EWMA, and its urgency is SLO burn.
The policy, in order:

1. **Feasibility first** — a tenant only lands where its footprint
   fits the host's free budget. An unbounded host (no budget) always
   fits.
2. **Priority beats heat** — pending tenants place highest-priority
   first (then largest-first, the classic bin-pack heuristic that
   avoids stranding big tenants behind small ones).
3. **Spread, don't stack** — among feasible hosts, pick the most free
   bytes (tie: fewest tenants, then lowest traffic): failover should
   not re-create the hot spot that just died.
4. **Pre-emption is a last resort** — when nothing fits, the planner
   may evict lower-priority UNPINNED tenants, coldest-first, but only
   on the single host where that actually frees enough room, and the
   evictees become pending placements themselves (they are displaced,
   not dropped).
5. **Refusal is honest** — a tenant with no feasible host (even after
   pre-emption) yields an explicit ``refuse`` decision with the
   reason; the controller surfaces it as an incident, it never
   silently disappears.

Every decision is a ``Decision`` record the controller writes to the
flight recorder verbatim — the plan IS the audit trail.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class TenantView:
    """One tenant as the planner sees it: identity + engine coords
    (enough to re-admit from registry lineage) + placement signals."""
    key: str
    hbm_bytes: int = 0
    priority: int = 0
    pinned: bool = False
    traffic_ewma: float = 0.0
    burn_fast: float = 0.0
    slo_status: str = "no_data"
    engine_id: str = ""
    engine_version: str = "0"
    engine_variant: str = "engine.json"
    engine_instance_id: str = ""
    generation: int = 0
    scheduler: Optional[dict] = None


@dataclass
class HostView:
    """One serving host: budget + current residents. ``budget_bytes``
    None means unbounded (a dev host without PIO_HBM_BUDGET)."""
    member_id: str
    url: str = ""
    budget_bytes: Optional[int] = None
    alive: bool = True
    tenants: Dict[str, TenantView] = field(default_factory=dict)

    def used_bytes(self) -> int:
        return sum(t.hbm_bytes for t in self.tenants.values())

    def free_bytes(self) -> Optional[int]:
        if self.budget_bytes is None:
            return None
        return self.budget_bytes - self.used_bytes()

    def fits(self, t: TenantView) -> bool:
        free = self.free_bytes()
        return free is None or t.hbm_bytes <= free


@dataclass(frozen=True)
class Decision:
    """One planned action. ``action`` is one of:

    - ``admit``   — place ``tenant`` on ``host``
    - ``migrate`` — move ``tenant`` from ``from_host`` to ``host``
    - ``preempt`` — evict ``tenant`` from ``from_host`` to make room
                    (paired with a later admit/refuse for the evictee)
    - ``refuse``  — no feasible host; ``reason`` says why
    """
    action: str
    tenant: str
    host: Optional[str] = None
    from_host: Optional[str] = None
    reason: str = ""

    def as_dict(self) -> dict:
        d = {"action": self.action, "tenant": self.tenant}
        if self.host:
            d["host"] = self.host
        if self.from_host:
            d["fromHost"] = self.from_host
        if self.reason:
            d["reason"] = self.reason
        return d


@dataclass
class PlacementPlan:
    decisions: List[Decision] = field(default_factory=list)

    @property
    def admits(self) -> List[Decision]:
        return [d for d in self.decisions if d.action == "admit"]

    @property
    def refusals(self) -> List[Decision]:
        return [d for d in self.decisions if d.action == "refuse"]

    def as_dict(self) -> dict:
        return {"decisions": [d.as_dict() for d in self.decisions]}


def _pick_host(hosts: Sequence[HostView], t: TenantView,
               exclude: Tuple[str, ...] = ()) -> Optional[HostView]:
    """Most-free feasible live host (spread-first). Unbounded hosts
    sort as infinitely free; ties break to fewest tenants, then least
    traffic, then member id for determinism."""
    best = None
    best_key = None
    for h in hosts:
        if not h.alive or h.member_id in exclude or not h.fits(t):
            continue
        free = h.free_bytes()
        key = (-(float("inf") if free is None else free),
               len(h.tenants),
               sum(x.traffic_ewma for x in h.tenants.values()),
               h.member_id)
        if best is None or key < best_key:
            best, best_key = h, key
    return best


def _preemption_victims(h: HostView, t: TenantView) -> List[TenantView]:
    """The cheapest set of lower-priority unpinned residents whose
    eviction makes ``t`` fit on ``h`` — coldest (lowest traffic EWMA)
    first, so pre-emption displaces the tenants least likely to
    notice. Empty list when no such set exists."""
    free = h.free_bytes()
    if free is None or t.hbm_bytes <= free:
        return []
    candidates = sorted(
        (x for x in h.tenants.values()
         if not x.pinned and x.priority < t.priority),
        key=lambda x: (x.traffic_ewma, -x.hbm_bytes, x.key))
    victims: List[TenantView] = []
    for v in candidates:
        victims.append(v)
        free += v.hbm_bytes
        if t.hbm_bytes <= free:
            return victims
    return []


def plan_placement(hosts: Sequence[HostView],
                   pending: Sequence[TenantView],
                   allow_preemption: bool = True) -> PlacementPlan:
    """Place every pending tenant onto the live hosts. Mutates NOTHING
    the caller passed in: hosts are shallow-copied with copied tenant
    maps so the simulation of successive placements stays internal."""
    sim = [replace_host(h) for h in hosts]
    plan = PlacementPlan()
    queue = sorted(pending,
                   key=lambda t: (-t.priority, -t.hbm_bytes, t.key))
    # displaced tenants re-enter the queue at most once: a pre-empted
    # tenant that cannot land anywhere becomes a refusal, it must not
    # pre-empt someone else and cascade forever
    displaced_once = set()
    i = 0
    while i < len(queue):
        t = queue[i]
        i += 1
        h = _pick_host(sim, t)
        if h is not None:
            h.tenants[t.key] = t
            plan.decisions.append(Decision(
                "admit", t.key, host=h.member_id,
                reason="fits free budget"))
            continue
        if allow_preemption and t.key not in displaced_once:
            # find the live host where evicting the cheapest set of
            # colder, lower-priority tenants frees enough room
            choice = None
            for cand in sorted(sim, key=lambda x: x.member_id):
                if not cand.alive:
                    continue
                victims = _preemption_victims(cand, t)
                if victims and (choice is None
                                or len(victims) < len(choice[1])):
                    choice = (cand, victims)
            if choice is not None:
                cand, victims = choice
                for v in victims:
                    del cand.tenants[v.key]
                    plan.decisions.append(Decision(
                        "preempt", v.key, from_host=cand.member_id,
                        reason=f"displaced by higher-priority "
                               f"{t.key}"))
                    displaced_once.add(v.key)
                    queue.append(v)
                cand.tenants[t.key] = t
                plan.decisions.append(Decision(
                    "admit", t.key, host=cand.member_id,
                    reason="fits after preemption"))
                continue
        plan.decisions.append(Decision(
            "refuse", t.key,
            reason="no feasible host: footprint %d bytes exceeds every "
                   "live host's free budget" % t.hbm_bytes))
    return plan


def replace_host(h: HostView) -> HostView:
    return HostView(member_id=h.member_id, url=h.url,
                    budget_bytes=h.budget_bytes, alive=h.alive,
                    tenants=dict(h.tenants))


def plan_failover(hosts: Sequence[HostView],
                  dead: HostView) -> PlacementPlan:
    """Re-place every tenant of ``dead`` onto the survivors. The dead
    host's roster comes from its corpse member record (the fleet
    registry keeps records of the dead on purpose)."""
    survivors = [h for h in hosts
                 if h.alive and h.member_id != dead.member_id]
    return plan_placement(survivors, list(dead.tenants.values()))


def plan_rebalance(hosts: Sequence[HostView],
                   pressure_ratio: float = 0.9) -> PlacementPlan:
    """Evict-cold/admit-hot ACROSS hosts: on every live host whose
    budget is under pressure (used/budget above ``pressure_ratio``),
    propose migrating its coldest unpinned tenant to the most-free
    peer that fits it. One migration per pressured host per planning
    round — the controller re-observes between rounds, so rebalancing
    converges on real signals instead of a stale simulation."""
    plan = PlacementPlan()
    sim = [replace_host(h) for h in hosts]
    for h in sorted(sim, key=lambda x: x.member_id):
        if not h.alive or h.budget_bytes is None or not h.tenants:
            continue
        if h.used_bytes() < pressure_ratio * h.budget_bytes:
            continue
        movable = sorted(
            (t for t in h.tenants.values() if not t.pinned),
            key=lambda t: (t.traffic_ewma, -t.hbm_bytes, t.key))
        for t in movable:
            dest = _pick_host(sim, t, exclude=(h.member_id,))
            if dest is None:
                continue
            del h.tenants[t.key]
            dest.tenants[t.key] = t
            plan.decisions.append(Decision(
                "migrate", t.key, host=dest.member_id,
                from_host=h.member_id,
                reason="evict-cold under budget pressure "
                       f"({h.used_bytes() + t.hbm_bytes}/"
                       f"{h.budget_bytes} bytes)"))
            break
    return plan
