"""Multi-tenant serving (ISSUE 15): many engines, one accelerator.

Production PredictionIO hosts many apps/engines behind one deployment
(the ``Apps``/``AccessKeys``/engine-instance metadata layer exists for
exactly this), while the TPU build's :class:`EngineServer` assumed one
model family per process. This package packs a *fleet* of engine
tenants onto one device:

- :mod:`tenancy.budget` — the HBM budget manager: per-tenant resident
  byte accounting over ``utils/device_cache``'s tenant-tagged uploads
  and residency slots, LRU/priority eviction of cold tenants' factor
  tables back to their host mirrors, and admission control that
  refuses a tenant whose padded tables can never fit
  (:class:`~predictionio_tpu.utils.device_cache.TableBudgetExceeded`).
- :mod:`tenancy.host` — the ServingHost: routes queries by app/engine
  key to per-tenant engine slots (each a full ``EngineServer`` with
  its own canary/rollback/last-known-good state, scheduler attachment
  and tenant-namespaced result-cache view), shares ONE compile-plane
  bucket ladder across tenants (identical shapes reuse executables),
  and serves per-tenant telemetry: ``pio_engine_hbm_bytes{tenant}``,
  ``pio_tenant_evictions_total{tenant,reason}``,
  ``pio_tenant_requests_total{tenant}``, a ``tenants`` block on
  ``/stats.json``, and the ``pio tenants {list,status,evict,pin}``
  CLI surfaces.
- :mod:`tenancy.placement` + :mod:`tenancy.controller` — the FLEET
  control plane (ISSUE 18): pure placement planning (bin-pack by HBM
  footprint with priority pre-emption) and the PlacementController
  that observes the member registry + per-tenant signals, fails a
  dead host's tenants over to survivors through the generation-fenced
  admit/remove endpoints, drives loss-free planned migrations, and
  feeds the :class:`~predictionio_tpu.tenancy.controller.TenantRouter`
  whose clients see slow, not 5xx, through a host kill.
- :mod:`tenancy.props` — durable per-tenant priority/pin sidecars
  (``pio tenants pin`` survives host restart).
- :mod:`tenancy.auth` — the ``PIO_AUTH=on`` access-key gate over
  ``/engines/<tenant>/queries.json`` (AccessKeys/Apps DAO validation,
  TTL-cached).
"""

from predictionio_tpu.tenancy.budget import (HBMBudgetManager,
                                             estimate_padded_bytes)
from predictionio_tpu.tenancy.host import (HostConfig, ServingHost,
                                           TenantSlot, TenantSpec)
from predictionio_tpu.tenancy.controller import (ControllerConfig,
                                                 PlacementController,
                                                 TenantRouter)
from predictionio_tpu.tenancy.placement import (Decision, HostView,
                                                PlacementPlan, TenantView,
                                                plan_failover,
                                                plan_placement,
                                                plan_rebalance)

__all__ = [
    "HBMBudgetManager", "estimate_padded_bytes",
    "HostConfig", "ServingHost", "TenantSlot", "TenantSpec",
    "ControllerConfig", "PlacementController", "TenantRouter",
    "Decision", "HostView", "PlacementPlan", "TenantView",
    "plan_failover", "plan_placement", "plan_rebalance",
]
