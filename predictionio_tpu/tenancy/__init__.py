"""Multi-tenant serving (ISSUE 15): many engines, one accelerator.

Production PredictionIO hosts many apps/engines behind one deployment
(the ``Apps``/``AccessKeys``/engine-instance metadata layer exists for
exactly this), while the TPU build's :class:`EngineServer` assumed one
model family per process. This package packs a *fleet* of engine
tenants onto one device:

- :mod:`tenancy.budget` — the HBM budget manager: per-tenant resident
  byte accounting over ``utils/device_cache``'s tenant-tagged uploads
  and residency slots, LRU/priority eviction of cold tenants' factor
  tables back to their host mirrors, and admission control that
  refuses a tenant whose padded tables can never fit
  (:class:`~predictionio_tpu.utils.device_cache.TableBudgetExceeded`).
- :mod:`tenancy.host` — the ServingHost: routes queries by app/engine
  key to per-tenant engine slots (each a full ``EngineServer`` with
  its own canary/rollback/last-known-good state, scheduler attachment
  and tenant-namespaced result-cache view), shares ONE compile-plane
  bucket ladder across tenants (identical shapes reuse executables),
  and serves per-tenant telemetry: ``pio_engine_hbm_bytes{tenant}``,
  ``pio_tenant_evictions_total{tenant,reason}``,
  ``pio_tenant_requests_total{tenant}``, a ``tenants`` block on
  ``/stats.json``, and the ``pio tenants {list,status,evict,pin}``
  CLI surfaces.
"""

from predictionio_tpu.tenancy.budget import (HBMBudgetManager,
                                             estimate_padded_bytes)
from predictionio_tpu.tenancy.host import (HostConfig, ServingHost,
                                           TenantSlot, TenantSpec)

__all__ = [
    "HBMBudgetManager", "estimate_padded_bytes",
    "HostConfig", "ServingHost", "TenantSlot", "TenantSpec",
]
