"""PlacementController: the fleet tenant control plane (ISSUE 18).

The planner (tenancy/placement.py) is pure; this module is everything
around it — observation, actuation, failover, and routing:

- **Observe**: read the fleet registry's member records (including the
  corpses — the registry keeps them deliberately), and for each live
  serving host fetch ``/placement.json`` (generations, specs, budget)
  and ``/tenants/signals.json`` (traffic EWMA, HBM bytes, SLO burn).
  A dead host's tenant roster comes OFF ITS MEMBER RECORD: the host
  republishes it on every admit/remove/pin precisely so that a SIGKILL
  leaves a forensically-complete corpse.
- **Failover**: a member record whose heartbeat went stale (or whose
  pid probe failed — the registry's liveness verdict, not ours) with
  tenants still in its roster triggers re-placement of every stranded
  tenant onto the survivors via the planner, actuated through the
  hosts' generation-fenced ``/tenants/<key>/admit`` endpoint. The
  admitting host reloads from registry lineage, AOT-warms before the
  slot is routable, and re-attaches the fold scheduler whose cursor
  resumes from the published lineage — detection to serving is
  bounded by one model load, and the whole episode lands as flight
  records plus ONE incident bundle naming the dead member and every
  re-placed tenant.
- **Planned migration**: quiesce → evict to host mirrors → admit on
  the target → route flip → remove from the source, every step fenced
  by a fresh placement generation so a stale route or a delayed retry
  can never act against a superseded placement. The source keeps
  serving (re-uploading from mirrors if queried) until the flip, so
  in-flight queries drain loss-free.
- **Routing**: :class:`TenantRouter` holds an O(1) tenant→URL map
  (swapped atomically, never locked on the query path) and retries
  under the stock :class:`~predictionio_tpu.resilience.RetryPolicy`,
  mapping stale-placement verdicts (404/409/503) to
  :class:`~predictionio_tpu.resilience.TransientHTTPError` after a
  route refresh — a client riding the router through a host kill sees
  added latency, never a 5xx.

Control decisions run on the controller's own thread; nothing here is
on any host's serve path.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.obs import FLIGHT, fleet, get_registry
from predictionio_tpu.resilience import RetryPolicy, TransientHTTPError
from predictionio_tpu.tenancy.placement import (HostView, PlacementPlan,
                                                TenantView, plan_failover,
                                                plan_placement,
                                                plan_rebalance)

logger = logging.getLogger(__name__)


def _post_json(url: str, body: dict,
               timeout: float = 60.0) -> Tuple[int, dict]:
    """POST JSON, returning (status, parsed body). HTTP error statuses
    come back as values (the caller decides what is fatal); transport
    failures raise OSError (retryable under the stock policy)."""
    data = json.dumps(body or {}).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, _parse(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, _parse(e.read())


def _parse(raw: bytes) -> dict:
    try:
        out = json.loads(raw or b"{}")
        return out if isinstance(out, dict) else {"body": out}
    except ValueError:
        return {"body": raw.decode("utf-8", "replace")}


def _fetch(url: str, timeout: float = 5.0) -> Optional[dict]:
    from predictionio_tpu.utils.http import fetch_json
    body = fetch_json(url, timeout=timeout)
    if not isinstance(body, dict) or "error" in body:
        return None
    return body


@dataclass
class ControllerConfig:
    #: control loop cadence; failover detection latency is this plus
    #: the registry's liveness window
    interval_s: float = 2.0
    #: budget for one remote admission (model load + AOT warm)
    admit_timeout_s: float = 120.0
    http_timeout_s: float = 5.0
    allow_preemption: bool = True


class PlacementController:
    """One control loop over the fleet's serving hosts."""

    def __init__(self, config: Optional[ControllerConfig] = None,
                 registry: Optional[fleet.FleetRegistry] = None):
        self.config = config or ControllerConfig()
        self.registry = registry or fleet.get_fleet()
        self._lock = threading.Lock()
        # tenant -> (url, member_id, generation): THE routing table.
        # Replaced wholesale under the lock, read without it — a
        # router's lookup is one dict get on an immutable snapshot.
        self._routes: Dict[str, tuple] = {}
        # highest placement generation seen per tenant (live placements
        # + corpse rosters); next_generation() fences every action
        self._gens: Dict[str, int] = {}
        # deaths already handled, keyed (memberId, startedAt): a corpse
        # record persists for an hour, the failover must run once
        self._handled: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._c_failovers = reg.counter(
            "pio_placement_failovers_total",
            "Host-death failovers the placement controller executed")
        self._c_refusals = reg.counter(
            "pio_placement_refusals_total",
            "Placement decisions refused for lack of a feasible host")
        self._c_migrations = reg.counter(
            "pio_placement_migrations_total",
            "Planned tenant migrations completed (evict -> admit -> "
            "route flip -> remove)")
        from predictionio_tpu.obs.slo import SLOEngine, \
            default_controller_specs
        self.slo = SLOEngine(default_controller_specs(),
                             registries=(reg,))

    # -- observation --------------------------------------------------------
    @staticmethod
    def _tenant_view(key: str, roster_entry: dict,
                     signals_row: Optional[dict] = None,
                     placement_row: Optional[dict] = None) -> TenantView:
        sig = signals_row or {}
        plc = placement_row or {}
        sched = roster_entry.get("scheduler")
        return TenantView(
            key=key,
            hbm_bytes=int(plc.get("expectedPaddedBytes")
                          or sig.get("hbmBytes") or 0),
            priority=int(roster_entry.get("priority") or 0),
            pinned=bool(roster_entry.get("pinned")),
            traffic_ewma=float(sig.get("trafficEwmaRps") or 0.0),
            burn_fast=float(sig.get("burnFast") or 0.0),
            slo_status=str(sig.get("sloStatus") or "no_data"),
            engine_id=roster_entry.get("engineId") or "",
            engine_version=str(roster_entry.get("engineVersion") or "0"),
            engine_variant=roster_entry.get("engineVariant")
            or "engine.json",
            engine_instance_id=roster_entry.get("engineInstanceId")
            or "",
            generation=int(roster_entry.get("generation") or 0),
            scheduler=dict(sched) if isinstance(sched, dict) else None)

    def observe(self) -> List[HostView]:
        """One consistent-enough snapshot of every serving host, dead
        or alive. Live hosts are asked for their placement + signals
        surfaces; a host that stops answering mid-observe degrades to
        its member-record roster (the same source a corpse uses)."""
        out: List[HostView] = []
        for m in self.registry.members(include_dead=True):
            if m.get("role") != "serving_host":
                continue
            url = fleet.member_url(m) or ""
            hv = HostView(member_id=m.get("memberId") or "",
                          url=url, alive=bool(m.get("alive")))
            hv.started_at = m.get("startedAt")   # death dedup key
            roster = m.get("tenants") or {}
            placement = signals = None
            if hv.alive and url:
                placement = _fetch(url + "/placement.json",
                                   self.config.http_timeout_s)
                signals = _fetch(url + "/tenants/signals.json",
                                 self.config.http_timeout_s)
            if placement is not None:
                hv.budget_bytes = placement.get("budgetBytes")
                # the live surface is fresher than the record roster
                roster = placement.get("tenants") or roster
            sig_rows = (signals or {}).get("tenants") or {}
            for key, entry in roster.items():
                if not isinstance(entry, dict):
                    continue
                hv.tenants[key] = self._tenant_view(
                    key, entry, sig_rows.get(key),
                    (placement or {}).get("tenants", {}).get(key))
            out.append(hv)
        with self._lock:
            for hv in out:
                for t in hv.tenants.values():
                    if t.generation > self._gens.get(t.key, 0):
                        self._gens[t.key] = t.generation
        return out

    def next_generation(self, tenant: str) -> int:
        with self._lock:
            g = self._gens.get(tenant, 0) + 1
            self._gens[tenant] = g
            return g

    # -- routing table ------------------------------------------------------
    def refresh_routes(self, hosts: Optional[List[HostView]] = None
                       ) -> Dict[str, tuple]:
        """Rebuild tenant -> (url, member, generation) from the LIVE
        placements; when a tenant appears on two hosts mid-migration,
        the newer generation wins (the fence guarantees the older one
        can no longer be acted on)."""
        if hosts is None:
            hosts = self.observe()
        routes: Dict[str, tuple] = {}
        for h in hosts:
            if not h.alive or not h.url:
                continue
            for t in h.tenants.values():
                cur = routes.get(t.key)
                if cur is None or t.generation >= cur[2]:
                    routes[t.key] = (h.url, h.member_id, t.generation)
        with self._lock:
            self._routes = routes
        return routes

    def route_for(self, tenant: str) -> Optional[tuple]:
        return self._routes.get(tenant)

    # -- failover -----------------------------------------------------------
    def _admit_body(self, t: TenantView, gen: int) -> dict:
        body = {
            "generation": gen,
            "engineId": t.engine_id or None,
            "engineVersion": t.engine_version,
            "engineVariant": t.engine_variant,
            "engineInstanceId": t.engine_instance_id or None,
            "priority": t.priority,
            "pinned": t.pinned,
        }
        if t.scheduler:
            body["scheduler"] = t.scheduler
        return body

    def _actuate_admit(self, host: HostView, t: TenantView,
                       gen: int) -> Tuple[bool, dict]:
        try:
            status, body = _post_json(
                f"{host.url}/tenants/{t.key}/admit",
                self._admit_body(t, gen),
                timeout=self.config.admit_timeout_s)
        except OSError as e:
            return False, {"error": str(e)}
        return status == 200, body

    def failover(self, dead: HostView,
                 survivors: List[HostView]) -> PlacementPlan:
        """Re-place every tenant stranded on ``dead`` onto the
        survivors. Tenants already serving on a live host (a previous
        partial failover, or a migration that raced the death) are
        skipped — the roster is where they WERE, the live placements
        are where they ARE."""
        live_keys = {k for h in survivors for k in h.tenants}
        stranded = HostView(member_id=dead.member_id, url=dead.url,
                            alive=False,
                            tenants={k: t for k, t in
                                     dead.tenants.items()
                                     if k not in live_keys})
        plan = plan_failover(survivors + [stranded], stranded)
        if not stranded.tenants:
            return plan
        by_member = {h.member_id: h for h in survivors}
        replaced, failed = [], []
        for d in plan.decisions:
            FLIGHT.record("placement_decision", tenant=d.tenant,
                          action=d.action, host=d.host,
                          fromHost=d.from_host or dead.member_id,
                          reason=d.reason, trigger="failover")
            if d.action == "refuse":
                self._c_refusals.inc()
                failed.append({"tenant": d.tenant, "reason": d.reason})
                continue
            if d.action != "admit":
                continue
            target = by_member.get(d.host)
            t = stranded.tenants.get(d.tenant) \
                or dead.tenants.get(d.tenant)
            if target is None or t is None:
                continue
            gen = self.next_generation(d.tenant)
            ok, body = self._actuate_admit(target, t, gen)
            if ok:
                replaced.append({"tenant": d.tenant,
                                 "host": d.host, "generation": gen,
                                 "modelVersion":
                                     body.get("modelVersion")})
            else:
                self._c_refusals.inc()
                failed.append({"tenant": d.tenant, "host": d.host,
                               "response": body})
        self._c_failovers.inc()
        self.refresh_routes()
        from predictionio_tpu.obs.incidents import get_incidents
        try:
            get_incidents().capture(
                "host_failover",
                reason=(f"serving host {dead.member_id} died; "
                        f"re-placed {len(replaced)}/"
                        f"{len(stranded.tenants)} stranded tenants: "
                        + ", ".join(sorted(stranded.tenants))),
                context={"deadMember": dead.member_id,
                         "deadStartedAt": getattr(dead, "started_at",
                                                  None),
                         "replaced": replaced, "failed": failed,
                         "plan": plan.as_dict()},
                sync=True)
        except Exception:
            logger.exception("failover incident capture failed")
        logger.warning("failover of %s: %d re-placed, %d failed",
                       dead.member_id, len(replaced), len(failed))
        return plan

    def step(self) -> dict:
        """One control iteration: observe, fail over any newly-dead
        host that still strands tenants, refresh routes."""
        hosts = self.observe()
        survivors = [h for h in hosts if h.alive]
        actions = []
        for h in hosts:
            if h.alive or not h.tenants:
                continue
            death_key = (h.member_id, getattr(h, "started_at", None))
            if death_key in self._handled:
                continue
            self._handled.add(death_key)
            plan = self.failover(h, survivors)
            actions.append({"failover": h.member_id,
                            "plan": plan.as_dict()})
        self.refresh_routes(None if actions else hosts)
        return {"hosts": len(hosts), "alive": len(survivors),
                "actions": actions}

    # -- planned migration --------------------------------------------------
    def migrate(self, tenant: str, to_member: str,
                hosts: Optional[List[HostView]] = None) -> dict:
        """Loss-free planned migration. Order matters:

        1. evict on the source — quiesce in-flight windows, drop
           device residency to host mirrors; the slot STAYS admitted
           and re-uploads if queried, so service never gaps;
        2. admit on the target under a fresh generation — load from
           lineage, AOT-warm; the target is ready before any traffic
           moves;
        3. route flip — atomic table swap, new queries go to the
           target;
        4. remove on the source under the same generation — drains
           the last in-flight queries through the slot gate, then
           frees the slot. A stale route hitting the source after
           this 404s (and a fenced query 409s), never serves.
        """
        if hosts is None:
            hosts = self.observe()
        by_member = {h.member_id: h for h in hosts if h.alive}
        target = by_member.get(to_member)
        source = next((h for h in hosts
                       if h.alive and tenant in h.tenants
                       and h.member_id != to_member), None)
        if target is None:
            raise ValueError(f"unknown or dead target {to_member!r}")
        if source is None:
            raise ValueError(
                f"tenant {tenant!r} is not on any live host "
                f"(other than the target)")
        t = source.tenants[tenant]
        gen = self.next_generation(tenant)
        FLIGHT.record("placement_decision", tenant=tenant,
                      action="migrate", host=to_member,
                      fromHost=source.member_id, generation=gen,
                      reason="planned migration", trigger="operator")
        status, body = _post_json(
            f"{source.url}/tenants/{tenant}/evict",
            {}, timeout=self.config.http_timeout_s * 4)
        if status != 200:
            raise RuntimeError(f"source evict failed: {body}")
        ok, body = self._actuate_admit(target, t, gen)
        if not ok:
            raise RuntimeError(f"target admit failed: {body}")
        with self._lock:
            routes = dict(self._routes)
            routes[tenant] = (target.url, target.member_id, gen)
            self._routes = routes
        status, rbody = _post_json(
            f"{source.url}/tenants/{tenant}/remove",
            {"generation": gen}, timeout=self.config.http_timeout_s * 4)
        if status != 200:
            # the tenant serves on the target either way; a failed
            # source removal is an operational leak, not data loss
            logger.error("source removal of %s on %s failed: %s",
                         tenant, source.member_id, rbody)
        self._c_migrations.inc()
        return {"tenant": tenant, "from": source.member_id,
                "to": target.member_id, "generation": gen,
                "modelVersion": body.get("modelVersion"),
                "sourceRemoved": status == 200}

    # -- planning surfaces (pio placement plan/apply) -----------------------
    def plan(self, pending: Optional[List[TenantView]] = None) -> dict:
        """A dry-run plan: rebalance proposals for the current fleet,
        plus placements for any explicitly-pending tenants."""
        hosts = self.observe()
        live = [h for h in hosts if h.alive]
        out = {"rebalance": plan_rebalance(live).as_dict()}
        if pending:
            out["placement"] = plan_placement(
                live, pending,
                allow_preemption=self.config.allow_preemption).as_dict()
        return out

    def apply_rebalance(self) -> List[dict]:
        """Execute the current rebalance plan's migrations, one
        observation per migration (plan_rebalance converges on real
        signals, not a stale simulation)."""
        done = []
        for _ in range(16):   # hard cap per apply
            hosts = self.observe()
            live = [h for h in hosts if h.alive]
            plan = plan_rebalance(live)
            moves = [d for d in plan.decisions
                     if d.action == "migrate"]
            if not moves:
                break
            d = moves[0]
            done.append(self.migrate(d.tenant, d.host, hosts=hosts))
        return done

    def status(self) -> dict:
        hosts = self.observe()
        with self._lock:
            routes = dict(self._routes)
        return {
            "hosts": [{
                "memberId": h.member_id, "url": h.url,
                "alive": h.alive,
                "budgetBytes": h.budget_bytes,
                "usedBytes": h.used_bytes(),
                "tenants": {k: {"generation": t.generation,
                                "priority": t.priority,
                                "pinned": t.pinned,
                                "hbmBytes": t.hbm_bytes,
                                "trafficEwmaRps": t.traffic_ewma,
                                "sloStatus": t.slo_status}
                            for k, t in sorted(h.tenants.items())},
            } for h in sorted(hosts, key=lambda h: h.member_id)],
            "routes": {t: {"url": u, "memberId": m, "generation": g}
                       for t, (u, m, g) in sorted(routes.items())},
            "slo": self.slo.evaluate(),
        }

    # -- control thread -----------------------------------------------------
    def start(self) -> "PlacementController":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.interval_s):
                try:
                    self.step()
                except Exception:
                    logger.exception("placement controller step failed")

        self._thread = threading.Thread(
            target=loop, daemon=True, name="pio-placement-controller")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class TenantRouter:
    """Client-side routing with retry-through-failover.

    ``query(tenant, body)`` looks the tenant up in the controller's
    O(1) route table, POSTs to the owning host with the placement
    generation attached (the host's fence turns a stale route into an
    honest 409), and maps every stale/transient verdict to a
    refreshed-route retry under the stock backoff policy — so a
    client calling through a host kill or a planned migration sees
    added latency, never a 5xx."""

    def __init__(self, controller: PlacementController,
                 policy: Optional[RetryPolicy] = None,
                 timeout_s: float = 10.0):
        self.controller = controller
        # deadline generous enough to ride out one failover (detection
        # + model reload); callers needing tighter bounds pass theirs
        self.policy = policy or RetryPolicy(
            max_attempts=8, base_delay_s=0.1, max_delay_s=2.0,
            deadline_s=90.0)
        self.timeout_s = timeout_s

    def _attempt(self, tenant: str, data: bytes) -> bytes:
        route = self.controller.route_for(tenant)
        if route is None:
            self.controller.refresh_routes()
            route = self.controller.route_for(tenant)
        if route is None:
            raise TransientHTTPError(
                f"no live placement for tenant {tenant!r}", status=503)
        url, _member, gen = route
        req = urllib.request.Request(
            f"{url}/engines/{tenant}/queries.json", data=data,
            method="POST",
            headers={"Content-Type": "application/json",
                     "X-PIO-Placement-Gen": str(gen)})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")[:200]
            if e.code in (404, 409, 429, 503):
                # the placement moved under us (or the host shed):
                # refresh and let the policy retry
                self.controller.refresh_routes()
                raise TransientHTTPError(
                    f"tenant {tenant!r} route stale ({e.code}): "
                    f"{detail}", status=e.code) from e
            raise
        except OSError:
            # connection refused/reset: the host just died — refresh
            # so the retry lands on a survivor (OSError is already in
            # the policy's TRANSIENT_ERRORS)
            self.controller.refresh_routes()
            raise

    def query(self, tenant: str, body: dict) -> dict:
        data = json.dumps(body).encode("utf-8")
        raw = self.policy.call(self._attempt, tenant, data)
        return json.loads(raw)
