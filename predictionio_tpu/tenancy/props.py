"""Durable per-tenant placement props (ISSUE 18 satellite).

``pio tenants pin`` used to flip a bit on the in-memory
``HBMBudgetManager`` ledger — gone on host restart, which made pinning
a tenant through a maintenance window impossible. This module is the
tiny lineage-props store that makes priority/pinned survive the
process: one crash-atomic JSON sidecar per tenant key under
``base_dir()/tenancy/props/``, written with the same
temp + fsync + os.replace discipline as the deploy guard's
last-good pin (online/registry.py), read back as an overlay on the
static ``TenantSpec`` at admit time.

Why sidecars and not an EngineInstances column: props describe the
TENANT (the serving placement identity), not any one trained instance
— a pin must survive retrains, rollbacks, and lineage republishes,
none of which should have to re-write placement intent. The store is
deliberately dumb: no locking beyond atomic replace (last writer wins,
and writers are the host's control endpoints, not the serve path).
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

#: props any caller may set; unknown keys are dropped on save so a
#: future reader never chokes on a foreign writer's experiment
_FIELDS = ("priority", "pinned")


def _props_dir() -> str:
    from predictionio_tpu.data.storage.registry import base_dir
    return os.path.join(base_dir(), "tenancy", "props")


def _path(tenant: str) -> str:
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", tenant or "_")
    return os.path.join(_props_dir(), f"{safe}.json")


def load_props(tenant: str) -> Optional[dict]:
    """The stored props for one tenant, or None when never written
    (callers then keep the spec's static defaults)."""
    try:
        with open(_path(tenant), encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def save_props(tenant: str, *, priority: Optional[int] = None,
               pinned: Optional[bool] = None) -> Optional[dict]:
    """Merge the given fields into the tenant's props sidecar,
    crash-atomically. Returns the record written, or None when the
    write failed (fail-soft: a read-only base_dir must not break the
    pin endpoint — the in-memory ledger still flips)."""
    rec = load_props(tenant) or {"tenant": tenant}
    if priority is not None:
        rec["priority"] = int(priority)
    if pinned is not None:
        rec["pinned"] = bool(pinned)
    rec = {k: rec[k] for k in ("tenant", *_FIELDS) if k in rec}
    rec["updatedAt"] = time.time()
    path = _path(tenant)
    tmp = path + f".tmp{os.getpid()}"
    try:
        os.makedirs(_props_dir(), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        logger.warning("tenancy: cannot persist props for %r under %s",
                       tenant, _props_dir(), exc_info=True)
        return None
    return rec


def all_props() -> Dict[str, dict]:
    """Every stored props record, keyed by tenant (for ``pio placement
    status`` and the controller's priority-aware planning)."""
    out: Dict[str, dict] = {}
    try:
        names = sorted(os.listdir(_props_dir()))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(_props_dir(), name),
                      encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and rec.get("tenant"):
            out[rec["tenant"]] = rec
    return out
