"""Distributed substrate: device mesh, shardings, host ingest.

This package replaces the reference's Spark runtime entry points
(reference: core/src/main/scala/io/prediction/workflow/WorkflowContext.scala:25-45
SparkContext creation; tools/Runner.scala:153-193 spark-submit): a
`jax.sharding.Mesh` over TPU devices is the cluster, GSPMD/XLA collectives
over ICI/DCN are the shuffle, and host-parallel event reads feeding
`jax.make_array_from_process_local_data` are the ingest edge.
"""

from predictionio_tpu.parallel.mesh import (MeshContext, current_mesh,
                                            make_mesh, use_mesh)

__all__ = ["MeshContext", "make_mesh", "current_mesh", "use_mesh"]
