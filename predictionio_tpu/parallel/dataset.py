"""Host-parallel ingest: event streams -> mesh-sharded device arrays.

The TPU-native replacement for the reference's HBase-scan-to-RDD edge
(reference: data/src/main/scala/io/prediction/data/storage/hbase/
HBPEvents.scala:42-80 `newAPIHadoopRDD`, and SURVEY.md §5 "Distributed
communication backend"): each host process reads its slice of the event
store, builds local numpy shards, and
`jax.make_array_from_process_local_data` assembles the global sharded
jax.Array over the mesh — no central driver ever holds the full data.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import numpy as np

from predictionio_tpu.parallel.mesh import MeshContext, current_mesh


def sharded_from_host(x: np.ndarray, mesh: Optional[MeshContext] = None,
                      axis: int = 0):
    """Single-process path: pad dim `axis` to the data-parallel degree and
    shard it over the mesh. Returns (array, original_len)."""
    mesh = mesh or current_mesh()
    padded, n = mesh.pad_to_multiple(np.asarray(x), axis=axis)
    return mesh.put_batch(padded), n


def sharded_from_process_local(local_shard: np.ndarray,
                               global_rows: int,
                               mesh: Optional[MeshContext] = None):
    """Multi-host path: every process passes only its local rows; JAX
    assembles the globally-sharded array (the make_array_from_process_local
    _data edge). Falls back to sharded_from_host when single-process."""
    import jax
    mesh = mesh or current_mesh()
    if jax.process_count() == 1:
        return sharded_from_host(local_shard, mesh)[0]
    sharding = mesh.batch_sharded(local_shard.ndim)
    global_shape = (global_rows,) + tuple(local_shard.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, local_shard, global_shape)


def events_to_ratings_arrays(events: Iterable,
                             rating_of=None
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                        np.ndarray]:
    """Stream (entityId, targetEntityId[, rating, t]) out of an event
    iterator into flat object/float arrays ready for EntityIdIxMap +
    RatingsCOO construction — the ingest half of every template DataSource,
    factored out so multi-host readers can shard the event scan by
    entity-hash range."""
    users, items, vals, ts = [], [], [], []
    from predictionio_tpu.data.event import to_millis
    for e in events:
        users.append(e.entity_id)
        items.append(e.target_entity_id)
        vals.append(rating_of(e) if rating_of else 1.0)
        ts.append(to_millis(e.event_time))
    return (np.array(users, dtype=object), np.array(items, dtype=object),
            np.array(vals, dtype=np.float32), np.array(ts, dtype=np.int64))
