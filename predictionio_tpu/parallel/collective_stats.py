"""Collective-traffic accounting from compiled XLA programs.

GSPMD decides where the collectives go; this module reads them back OUT
of the compiled HLO so multi-chip communication cost is a measured
property of the actual program, not an assumption. Used by
``__graft_entry__.dryrun_multichip`` (the in-env weak-scaling proxy: no
multi-chip hardware is reachable here, but the compiled program's
collective bytes + the chip's published ICI bandwidth bound the scaling
loss) and available to operators via ``bench.py --mesh-sweep``.

Role in the reference stack: the Spark UI's shuffle read/write metrics —
the thing an MLlib operator watches to see communication cost
(reference: the block-ALS shuffle in
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/ALSAlgorithm.scala:55's
``ALS.train``); here the "shuffle" is XLA collectives over ICI.
"""

from __future__ import annotations

import re
from typing import Dict

_DTYPE_BYTES = {
    "f64": 8, "s64": 8, "u64": 8,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

# optimized TPU HLO splits collectives into async -start/-done pairs;
# count the -start (it carries the shape) and ignore the -done
_LINE_RE = re.compile(
    r"= ((?:\([^)]*\)|[a-z0-9]+\[[^\]]*\])\S*) "
    r"(" + "|".join(_COLLECTIVES) + r")(-start)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")


def _shape_bytes(shapes_txt: str, largest_only: bool = False) -> int:
    """Sum (or max, for async -start tuples whose elements are operand +
    result + scratch and would double-count the payload) of the element
    buffer sizes in an HLO shape string."""
    sizes = []
    for dt, dims in _SHAPE_RE.findall(shapes_txt):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _DTYPE_BYTES.get(dt, 4))
    if not sizes:
        return 0
    return max(sizes) if largest_only else sum(sizes)


def collective_stats(compiled_or_text) -> Dict[str, dict]:
    """Per-collective-op instruction counts and output bytes of a compiled
    XLA program (pass a ``jax.stages.Compiled`` or its ``as_text()``).

    Bytes are the collective OUTPUT buffer sizes — for all-reduce the
    payload each participant contributes/receives, for all-gather the
    gathered result. This is the on-the-wire lower bound per ring pass;
    actual link traffic for a ring all-reduce is ~2x (reduce-scatter +
    all-gather phases), which ``ici_seconds`` accounts for."""
    text = (compiled_or_text if isinstance(compiled_or_text, str)
            else compiled_or_text.as_text())
    out: Dict[str, dict] = {}
    for line in text.splitlines():
        m = _LINE_RE.search(line)
        if not m:
            continue
        op = m.group(2)
        b = _shape_bytes(m.group(1), largest_only=bool(m.group(3)))
        ent = out.setdefault(op, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += b
    out["total"] = {
        "count": sum(v["count"] for v in out.values()),
        "bytes": sum(v["bytes"] for v in out.values()),
    }
    return out


def ici_seconds(stats: Dict[str, dict], n_devices: int,
                ici_bytes_per_s: float = 200e9) -> float:
    """Lower-bound wall time the program's collectives spend on ICI.

    Ring-algorithm cost per collective of payload P over n devices:
    all-reduce moves ~2*P*(n-1)/n per link, all-gather/reduce-scatter
    ~P*(n-1)/n, collective-permute/all-to-all ~P. Default bandwidth is
    the v5e published per-chip ICI figure (1600 Gbps = 200 GB/s);
    pass the target chip's number for others."""
    if n_devices <= 1:
        return 0.0
    scale = (n_devices - 1) / n_devices
    total = 0.0
    for op, ent in stats.items():
        if op == "total":
            continue
        p = ent["bytes"]
        if op == "all-reduce":
            total += 2.0 * p * scale
        elif op in ("all-gather", "reduce-scatter"):
            total += p * scale
        else:
            total += p
    return total / ici_bytes_per_s
