"""Host-side handle to a model-axis-sharded factor table.

The ALX discipline (PAPERS.md "Large Scale Matrix Factorization on
TPUs") keeps embedding tables sharded across the mesh and device-
resident across steps; the host never holds — or moves — the whole
table. ``ShardedTable`` is what a published model version carries in
place of one monolithic numpy array:

- **per-shard host slices** (``shards`` + ``offsets``): the durable
  mirror the registry serializes, the gates probe, and a restarted
  server re-uploads from. In a multi-process mesh each process holds
  only its addressable shards; single-process holds all of them.
- **a transient device handle** (``_dev``): the resident fast path.
  A fold tick publishes the tick's final device arrays here, so the
  next tick — and serving — reuse them without any host round trip.
  The handle is never pickled (``__getstate__`` drops it) and is
  revalidated against the mesh before reuse.

Steady-state fold ticks update the mirror **copy-on-write per shard**:
only shards containing touched rows are copied and patched (host
memcpy), and only the touched rows themselves cross the device->host
link. The table as a whole never moves — the property the over-budget
acceptance scenario asserts via ``pio_fold_upload_bytes_total``.

Tables are immutable: hot-swap/rollback replace the whole object, so
a query thread can never observe a half-patched shard set (the same
no-torn-read contract replicated models get from numpy immutability
by convention).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def is_sharded(table) -> bool:
    """True when ``table`` is a ShardedTable (the layout dispatch every
    serve/fold/gate call site keys on)."""
    return isinstance(table, ShardedTable)


def table_rows(table, idx) -> np.ndarray:
    """Host gather of global rows from either layout: shard mirrors
    for a ShardedTable, plain fancy-indexing for numpy."""
    if is_sharded(table):
        return table.rows(idx)
    return np.asarray(table)[np.asarray(idx, dtype=np.int64)]


def layout_of(table) -> str:
    """'model:<N>' for an N-way sharded table, else 'replicated' — the
    sharding token residency slots and caches key on."""
    if is_sharded(table):
        return f"model:{table.n_shards}"
    return "replicated"


class ShardedTable:
    """Row-partitioned factor table: ``n_shards`` contiguous row ranges
    of a ``[padded_rows, rank]`` table, rows ``>= n_rows`` being bucket
    padding (zeros). Immutable by convention — mutators return new
    tables sharing untouched shard arrays."""

    def __init__(self, shards: Sequence[np.ndarray],
                 offsets: Sequence[int], n_rows: int, padded_rows: int,
                 n_shards: int):
        self.shards: Tuple[np.ndarray, ...] = tuple(
            np.ascontiguousarray(s, dtype=np.float32) for s in shards)
        self.offsets: Tuple[int, ...] = tuple(int(o) for o in offsets)
        self.n_rows = int(n_rows)
        self.padded_rows = int(padded_rows)
        self.n_shards = int(n_shards)
        if not self.shards:
            raise ValueError("ShardedTable needs at least one shard")
        if padded_rows % self.n_shards:
            raise ValueError(
                f"padded rows {padded_rows} not divisible by "
                f"{self.n_shards} shards")
        self._dev = None          # transient device handle (never pickled)
        # serializes the cold-path upload: N serve threads racing a
        # restart must not each materialize the table (transient N x
        # per-device HBM — the overcommit the budget exists to stop)
        self._dev_lock = threading.Lock()

    # -- numpy-facing surface ------------------------------------------------
    @property
    def rank(self) -> int:
        return self.shards[0].shape[1]

    @property
    def shape(self) -> Tuple[int, int]:
        """The LOGICAL shape (bucket padding excluded) — what
        ``ALSModel.n_users``/``n_items`` and the gates read."""
        return (self.n_rows, self.rank)

    @property
    def ndim(self) -> int:
        return 2

    @property
    def dtype(self):
        return self.shards[0].dtype

    @property
    def size(self) -> int:
        return self.n_rows * self.rank

    @property
    def nbytes(self) -> int:
        """Logical table bytes (what a replicated copy would cost)."""
        return self.n_rows * self.rank * self.dtype.itemsize

    @property
    def per_shard_nbytes(self) -> int:
        """Padded bytes ONE device holds — the number the per-device
        table budget compares against."""
        return (self.padded_rows // self.n_shards) * self.rank \
            * self.dtype.itemsize

    def __len__(self) -> int:
        return self.n_rows

    def __repr__(self) -> str:
        return (f"ShardedTable(rows={self.n_rows}/{self.padded_rows}, "
                f"rank={self.rank}, shards={self.n_shards}, "
                f"resident={self._dev is not None})")

    # -- constructors --------------------------------------------------------
    @staticmethod
    def from_host(arr: np.ndarray, n_shards: int,
                  padded_rows: Optional[int] = None) -> "ShardedTable":
        """Split one host table into ``n_shards`` equal row slices,
        zero-padded to ``padded_rows`` (default: the covering sharded
        vocab bucket). The entry path for converting a replicated model
        to the sharded layout."""
        from predictionio_tpu.compile.buckets import bucket_rows_sharded
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        n = arr.shape[0]
        target = padded_rows if padded_rows is not None \
            else bucket_rows_sharded(max(n, 1), n_shards)
        if target < n or target % n_shards:
            raise ValueError(
                f"padded_rows {target} must cover {n} rows and divide "
                f"by {n_shards}")
        per = target // n_shards
        shards = []
        for s in range(n_shards):
            lo, hi = s * per, (s + 1) * per
            block = np.zeros((per, arr.shape[1]), dtype=np.float32)
            got = arr[lo:min(hi, n)]
            block[:got.shape[0]] = got
            shards.append(block)
        return ShardedTable(shards, [s * per for s in range(n_shards)],
                            n, target, n_shards)

    # -- host row access -----------------------------------------------------
    def _which_shard(self, idx: np.ndarray) -> np.ndarray:
        """Shard index (into ``self.shards``) owning each global row;
        raises IndexError for rows no addressable shard covers (a
        multi-process follower holds only its slices — a negative or
        past-the-slice lookup must fail loudly, never wrap into the
        wrong shard's rows)."""
        offs = np.asarray(self.offsets, dtype=np.int64)
        which = np.searchsorted(offs, idx, side="right") - 1
        if (which < 0).any():
            raise IndexError(
                f"rows {idx[which < 0]} precede this process's "
                f"addressable shards (offsets {self.offsets})")
        ends = offs + np.asarray([s.shape[0] for s in self.shards],
                                 dtype=np.int64)
        past = idx >= ends[which]
        if past.any():
            raise IndexError(
                f"rows {idx[past]} fall outside this process's "
                f"addressable shards (offsets {self.offsets})")
        return which

    def _require_full_coverage(self, what: str):
        if self.offsets[0] != 0 or sum(
                s.shape[0] for s in self.shards) != self.padded_rows:
            raise ValueError(
                f"{what} needs every shard addressable "
                f"(single-process); this process holds offsets "
                f"{self.offsets} of {self.padded_rows} rows")

    def rows(self, idx) -> np.ndarray:
        """Gather global rows from the host shard mirrors (the gates'
        probe path and the serve-side user-vector lookup — no device
        involved). Raises IndexError for rows outside the addressable
        shards (multi-process callers own only their slices)."""
        idx = np.asarray(idx, dtype=np.int64).ravel()
        out = np.empty((idx.size, self.rank), dtype=np.float32)
        if idx.size == 0:
            return out
        if (idx < 0).any() or (idx >= self.padded_rows).any():
            raise IndexError(f"row index out of range 0..{self.padded_rows}")
        which = self._which_shard(idx)
        offs = np.asarray(self.offsets, dtype=np.int64)
        for s in np.unique(which):
            sel = which == s
            out[sel] = self.shards[s][idx[sel] - offs[s]]
        return out

    def to_numpy(self) -> np.ndarray:
        """Materialize the FULL logical table on host — an explicit
        O(table) host concat for parity tests / checkpoint export, not
        a serve- or tick-path operation."""
        self._require_full_coverage("to_numpy")
        return np.concatenate(self.shards, axis=0)[:self.n_rows]

    def all_finite(self) -> bool:
        return all(np.isfinite(self._logical_view(i)).all()
                   for i in range(len(self.shards)))

    def max_row_norm(self) -> float:
        mx = 0.0
        for i in range(len(self.shards)):
            t = self._logical_view(i)
            if t.size == 0:
                continue
            with np.errstate(over="ignore", invalid="ignore"):
                n = float(np.sqrt(np.max(np.einsum("ij,ij->i", t, t))))
            if np.isfinite(n):
                mx = max(mx, n)
        return mx

    def _logical_view(self, i: int) -> np.ndarray:
        """Shard ``i`` minus bucket-padding rows (zero rows past
        ``n_rows`` must not influence finiteness/norm verdicts...
        they are zero, but a patched-row write past n_rows could)."""
        off = self.offsets[i]
        stop = max(min(self.n_rows - off, self.shards[i].shape[0]), 0)
        return self.shards[i][:stop]

    # -- mutation (copy-on-write) -------------------------------------------
    def with_rows(self, idx, values: np.ndarray,
                  n_rows: Optional[int] = None) -> "ShardedTable":
        """New table with global rows ``idx`` replaced by ``values``:
        only shards containing touched rows are copied (host memcpy of
        O(touched shards), never the device link). ``n_rows`` grows the
        logical size inside the same bucket."""
        idx = np.asarray(idx, dtype=np.int64).ravel()
        values = np.asarray(values, dtype=np.float32)
        which = self._which_shard(idx)
        offs = np.asarray(self.offsets, dtype=np.int64)
        shards = list(self.shards)
        for s in np.unique(which):
            sel = which == s
            patched = shards[s].copy()
            patched[idx[sel] - offs[s]] = values[sel]
            shards[s] = patched
        return ShardedTable(shards, self.offsets,
                            self.n_rows if n_rows is None else n_rows,
                            self.padded_rows, self.n_shards)

    def grown(self, n_rows: int, padded_rows: int) -> "ShardedTable":
        """Re-partition for a bucket promotion (``padded_rows`` grew):
        shard boundaries move, so this is the one O(table) host
        reshuffle — paid once per 2x vocabulary growth, like the
        compile the promotion also pays. Single-process only (a
        follower holding a subset of shards cannot re-partition
        without cross-process data movement — refuse rather than
        silently misattribute rows)."""
        self._require_full_coverage("grown")
        full = np.concatenate(self.shards, axis=0)
        grown = np.zeros((padded_rows, self.rank), dtype=np.float32)
        grown[:full.shape[0]] = full
        out = ShardedTable.from_host(grown, self.n_shards,
                                     padded_rows=padded_rows)
        return ShardedTable(out.shards, out.offsets, n_rows,
                            padded_rows, self.n_shards)

    # -- device residency ----------------------------------------------------
    def device(self, mesh, target_rows: Optional[int] = None,
               record_upload=None):
        """The model-sharded device array for this table: the attached
        resident handle when it is still valid for ``mesh`` (and the
        requested row bucket), else an upload of the host shards (each
        process materializes only its addressable slices —
        ``make_array_from_callback``). The upload is the COLD path
        (restart, mesh change); steady-state ticks and serving always
        hit the handle.

        ``target_rows`` > ``padded_rows`` uploads AT the larger row
        bucket, zero-filling the extra rows inside the upload callback
        — the serve path's way to cover a table whose own padding is
        below its covering sharded bucket (e.g. a just-trained table)
        WITHOUT mutating the published model or re-partitioning the
        host mirrors (real promotions — where the mirrors must follow
        because the publish patches them — stay ``grown()``'s job, on
        the fold tick)."""
        target = max(int(target_rows or 0), self.padded_rows)
        if target % self.n_shards:
            raise ValueError(
                f"target_rows {target} not divisible by "
                f"{self.n_shards} shards")

        def _valid(dev):
            return dev is not None and dev.shape[0] == target \
                and getattr(dev.sharding, "mesh", None) == mesh.mesh

        dev = self._dev
        if _valid(dev):
            return dev
        with self._dev_lock:
            dev = self._dev       # a racing thread may have uploaded
            if _valid(dev):
                return dev
            from predictionio_tpu.utils.device_cache import \
                check_table_budget
            check_table_budget(
                (target // self.n_shards) * self.rank
                * self.dtype.itemsize, table=repr(self))
            import jax
            sharding = mesh.model_sharded(2)
            shape = (target, self.rank)

            def _cb(index):
                rows = index[0]
                start = rows.start or 0
                stop = rows.stop if rows.stop is not None else shape[0]
                return self._host_rows(start, stop)

            dev = jax.make_array_from_callback(shape, sharding, _cb)
            if record_upload is None:
                from predictionio_tpu.obs import jaxmon
                record_upload = jaxmon.record_h2d
            record_upload(target * self.rank * self.dtype.itemsize)
            self._dev = dev
            return dev

    def _host_rows(self, start: int, stop: int) -> np.ndarray:
        """Contiguous global rows from the addressable shard slices;
        rows past ``padded_rows`` (a larger upload bucket's tail) are
        zeros."""
        parts = []
        need = start
        for off, sh in zip(self.offsets, self.shards):
            lo, hi = max(start, off), min(stop, off + sh.shape[0])
            if lo < hi:
                if lo != need:
                    break
                parts.append(sh[lo - off:hi - off])
                need = hi
        if need < stop and need >= self.padded_rows:
            parts.append(np.zeros((stop - need, self.rank),
                                  dtype=np.float32))
            need = stop
        if need != stop:
            raise IndexError(
                f"rows [{start}, {stop}) not covered by addressable "
                f"shards (offsets {self.offsets})")
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def attach_device(self, dev) -> "ShardedTable":
        """Bind the tick's final device array as the resident fast
        path (mutates only the transient handle — the host mirror and
        identity of ``self`` are unchanged)."""
        self._dev = dev
        return self

    def drop_device(self) -> int:
        """Release the transient device handle (tenant eviction,
        tenancy/budget.py): the host mirrors stay the source of truth
        and the next :meth:`device` call re-uploads through the
        budget-checked cold path. An in-flight dispatch that already
        closed over the handle keeps its own reference — dropping here
        only stops pinning HBM for future calls. Returns the per-device
        bytes the handle was pinning (0 when none was resident)."""
        with self._dev_lock:
            freed = self.device_nbytes()
            self._dev = None
        return freed

    def device_nbytes(self) -> int:
        """Per-device bytes pinned by the resident handle (0 when not
        resident) — the tenancy budget manager's sharded-table sizer."""
        dev = self._dev
        if dev is None:
            return 0
        from predictionio_tpu.utils.device_cache import _device_nbytes
        return _device_nbytes(dev)

    # -- pickling ------------------------------------------------------------
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_dev"] = None       # device handles never serialize
        state.pop("_dev_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._dev = None
        self._dev_lock = threading.Lock()


def sharding_meta(models) -> Optional[dict]:
    """``{"layout": "model", "shards": N}`` when any model in the set
    carries sharded factor tables — the lineage tag the registry
    publishes so `pio status` / a restarted follower can tell the
    layouts apart without deserializing the blob."""
    for m in models:
        for obj in (m, getattr(m, "als", None)):
            if obj is None:
                continue
            for attr in ("user_factors", "item_factors"):
                t = getattr(obj, attr, None)
                if is_sharded(t):
                    return {"layout": "model", "shards": t.n_shards}
    return None
