"""Device mesh management and sharding helpers.

The SparkContext analog (reference: workflow/WorkflowContext.scala:25-45).
A `MeshContext` owns a `jax.sharding.Mesh` with two named axes:

  - ``data``  — batch-dimension parallelism (rows of users/items/events);
                the analog of Spark's RDD partitioning.
  - ``model`` — parameter sharding (embedding-table rows, hidden dims);
                no Spark analog (MLlib block ALS plays this role).

Kernels request shardings by logical spec; XLA/GSPMD inserts the ICI/DCN
collectives. Multi-host initialization goes through `jax.distributed` —
`init_distributed` is the `spark-submit --master` analog.
"""

from __future__ import annotations

import contextlib
import logging
import math
import os
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_local = threading.local()


# per-user default (a shared predictable /tmp path would allow cross-user
# cache poisoning); JAX_COMPILATION_CACHE_DIR overrides
DEFAULT_COMPILE_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "pio_tpu", "xla")
_compile_cache_lock = threading.Lock()
_compile_cache_set = False


def configure_compilation_cache() -> None:
    """Point jax at the persistent compilation cache so warmup compiles
    are paid once per machine. Called at CLI process init and again
    lazily from _jax() (env vars may be latched before we run —
    sitecustomize imports jax at interpreter start — so this goes through
    jax.config). Safe to call repeatedly/concurrently.

    Delegates to the compile plane's managed cache (ISSUE 9,
    compile/cache.py: salted dir under ``base_dir()/xla_cache``,
    hit/miss counters, ``pio cache`` lifecycle); the legacy per-user
    ``~/.cache/pio_tpu/xla`` path remains only as the fallback when the
    compile plane is unavailable."""
    global _compile_cache_set
    if _compile_cache_set:
        return
    try:
        from predictionio_tpu.compile.cache import (cache_disabled,
                                                    enable_persistent_cache)
        if cache_disabled():
            _compile_cache_set = True    # operator kill switch: no cache
            return
        if enable_persistent_cache() is not None:
            _compile_cache_set = True
            return
        # enable failed internally (unwritable base_dir, config error):
        # fall through to the legacy per-user path rather than silently
        # running with no cache at all
        logger.debug("compile-plane cache enable failed; legacy path")
    except Exception:
        logger.debug("compile-plane cache unavailable; legacy path",
                     exc_info=True)
    with _compile_cache_lock:
        if _compile_cache_set:
            return
        import jax
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                   DEFAULT_COMPILE_CACHE_DIR)
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            _compile_cache_set = True
        except Exception:
            logger.debug("compilation cache dir not set", exc_info=True)
            _compile_cache_set = True


def _jax():
    import jax
    if not _compile_cache_set:
        configure_compilation_cache()
    return jax


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (jax.distributed.initialize). No-op when
    single-process. Driven by PIO_COORDINATOR/PIO_NUM_PROCESSES/PIO_PROCESS_ID
    or explicit args — the env-passthrough analog of Runner.scala:105-108."""
    jax = _jax()
    coordinator = coordinator or os.environ.get("PIO_COORDINATOR")
    if coordinator is None:
        return
    num_processes = num_processes or int(os.environ["PIO_NUM_PROCESSES"])
    process_id = process_id or int(os.environ["PIO_PROCESS_ID"])
    # CPU multi-process meshes need an explicit cross-host collectives
    # implementation: the default XLA CPU client answers every
    # multi-process computation with "Multiprocess computations aren't
    # implemented on the CPU backend". jaxlib ships gloo for exactly
    # this; select it BEFORE the backend initializes (the config
    # latches at first device use). TPU/GPU backends have their own
    # fabric and ignore this knob; older/newer jax without the option
    # falls through untouched.
    if num_processes > 1 and os.environ.get(
            "JAX_PLATFORMS", "").strip().lower() == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            logger.debug("cpu collectives implementation not "
                         "configurable on this jax", exc_info=True)
    jax.distributed.initialize(coordinator, num_processes, process_id)
    logger.info("jax.distributed initialized: process %d/%d via %s",
                process_id, num_processes, coordinator)


class MeshContext:
    """A named-axis device mesh plus sharding constructors."""

    DATA_AXIS = "data"
    MODEL_AXIS = "model"

    def __init__(self, mesh):
        self.mesh = mesh

    # -- constructors -------------------------------------------------------
    @staticmethod
    def create(devices=None, model_parallelism: int = 1) -> "MeshContext":
        jax = _jax()
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        if n % model_parallelism != 0:
            raise ValueError(
                f"model_parallelism {model_parallelism} does not divide "
                f"device count {n}")
        dp = n // model_parallelism
        arr = np.array(devices).reshape(dp, model_parallelism)
        mesh = jax.sharding.Mesh(
            arr, (MeshContext.DATA_AXIS, MeshContext.MODEL_AXIS))
        return MeshContext(mesh)

    # -- properties ---------------------------------------------------------
    @property
    def n_devices(self) -> int:
        return int(math.prod(self.mesh.devices.shape))

    @property
    def data_parallelism(self) -> int:
        return self.mesh.shape[self.DATA_AXIS]

    @property
    def model_parallelism(self) -> int:
        return self.mesh.shape[self.MODEL_AXIS]

    # -- sharding constructors ---------------------------------------------
    def sharding(self, *axis_per_dim) -> "object":
        """NamedSharding with the given mesh axis (or None) per array dim."""
        jax = _jax()
        return jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(*axis_per_dim))

    def replicated(self):
        jax = _jax()
        return jax.sharding.NamedSharding(self.mesh,
                                          jax.sharding.PartitionSpec())

    def batch_sharded(self, ndim: int = 1):
        """First dim sharded over the data axis, rest replicated."""
        return self.sharding(self.DATA_AXIS, *([None] * (ndim - 1)))

    def model_sharded(self, ndim: int = 1):
        """First dim sharded over the model axis (embedding-table rows)."""
        return self.sharding(self.MODEL_AXIS, *([None] * (ndim - 1)))

    # -- data movement ------------------------------------------------------
    def put(self, x, sharding):
        """Host array -> device array with the given sharding. Single
        process uses device_put; multi-process goes through
        make_array_from_callback, where each process materializes only its
        addressable shards — device_put's cross-process assert_equal
        collective both costs an allgather of the full array and (observed
        on the gloo CPU backend) false-positives on identical inputs."""
        jax = _jax()
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])

    def put_batch(self, x):
        """Host array -> device array sharded on dim 0 over the data axis.
        dim 0 must be divisible by data_parallelism (use pad_to_multiple)."""
        return self.put(x, self.batch_sharded(np.ndim(x)))

    def put_replicated(self, x):
        return self.put(x, self.replicated())

    def put_stacked(self, x):
        """Host array -> device array sharded on dim 1 over the data axis:
        the layout of stacked same-shape batch groups [N, B, ...] that a
        `lax.scan` consumes along dim 0, each slice staying data-sharded."""
        ndim = np.ndim(x)
        return self.put(
            x, self.sharding(None, self.DATA_AXIS, *([None] * (ndim - 2))))

    def put_model_sharded(self, x):
        """Rows sharded over the model axis (embedding tables)."""
        return self.put(x, self.model_sharded(np.ndim(x)))

    def pad_to_multiple(self, x: np.ndarray, axis: int = 0,
                        multiple: Optional[int] = None,
                        fill=0) -> Tuple[np.ndarray, int]:
        """Pad so dim `axis` divides the data-axis size; returns (padded,
        original_len). The ragged->fixed-shape edge (SURVEY hard part #3)."""
        multiple = multiple or self.data_parallelism
        n = x.shape[axis]
        target = ((n + multiple - 1) // multiple) * multiple
        if target == n:
            return x, n
        pad_width = [(0, 0)] * x.ndim
        pad_width[axis] = (0, target - n)
        return np.pad(x, pad_width, constant_values=fill), n


def host_fetch(x) -> np.ndarray:
    """Device array -> host numpy, multi-process safe: a replicated array
    spanning remote processes is not fully addressable, but every local
    shard holds the complete value."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    shard = x.addressable_data(0)
    if shard.shape != x.shape:
        raise ValueError(
            f"host_fetch needs a replicated array; got sharded shape "
            f"{shard.shape} of global {x.shape} — use "
            f"host_fetch_sharded to gather the per-shard slices this "
            f"process can address")
    return np.asarray(shard)


def host_fetch_sharded(x):
    """Device array sharded on dim 0 -> the per-shard host slices this
    process can address, as ``(offsets, slices)`` sorted by global row
    offset. Replicas (e.g. a model-sharded table's copies across the
    data axis) are deduplicated by offset — each row range is fetched
    once. The sharded sibling of :func:`host_fetch`: where that gathers
    one complete value, this hands back exactly the slices a
    ``ShardedTable`` host mirror wants, with no cross-shard gather and
    no remote-process traffic."""
    shards = getattr(x, "addressable_shards", None)
    if shards is None:
        return [0], [np.asarray(x)]
    by_offset = {}
    for sh in shards:
        index = sh.index or (slice(None),)
        # only dim-0 partitioning is a row sharding: an array split on
        # a LATER dim has every shard at row offset 0, and deduping by
        # that offset would silently return one partial shard as the
        # whole value — refuse instead (host_fetch's loud-misuse
        # discipline)
        for d, dim_slice in enumerate(index[1:], start=1):
            full = (dim_slice.start in (None, 0)
                    and dim_slice.stop in (None, x.shape[d]))
            if not full:
                raise ValueError(
                    f"host_fetch_sharded needs an array sharded on "
                    f"dim 0 only; got shard index {index} of global "
                    f"{x.shape}")
        rows = index[0]
        start = rows.start or 0
        if start not in by_offset:
            by_offset[start] = np.asarray(sh.data)
    offsets = sorted(by_offset)
    return offsets, [by_offset[o] for o in offsets]


def make_mesh(devices=None, model_parallelism: int = 1) -> MeshContext:
    return MeshContext.create(devices, model_parallelism)


def current_mesh() -> MeshContext:
    """The active mesh; lazily creates a full-device 1x data mesh."""
    ctx = getattr(_local, "mesh", None)
    if ctx is None:
        ctx = make_mesh()
        _local.mesh = ctx
    return ctx


_model_mesh_lock = threading.Lock()
_model_meshes: dict = {}


def model_mesh(n_shards: int) -> MeshContext:
    """A mesh whose model axis is ``n_shards`` wide — the mesh a
    model-sharded table serves and folds on. The thread's active mesh
    wins when its model axis already matches (tests and explicit
    ``use_mesh`` scopes); otherwise a PROCESS-wide mesh per shard
    count is built and cached, so every server thread resolves the
    SAME mesh for the same layout (``current_mesh``'s thread-local
    default would hand each HTTP handler thread its own 1-wide model
    axis and silently re-replicate a sharded table)."""
    ctx = getattr(_local, "mesh", None)
    if ctx is not None and ctx.model_parallelism == n_shards:
        return ctx
    with _model_mesh_lock:
        ctx = _model_meshes.get(n_shards)
        if ctx is None:
            ctx = make_mesh(model_parallelism=n_shards)
            _model_meshes[n_shards] = ctx
        return ctx


@contextlib.contextmanager
def use_mesh(ctx: MeshContext):
    prev = getattr(_local, "mesh", None)
    _local.mesh = ctx
    try:
        yield ctx
    finally:
        _local.mesh = prev
